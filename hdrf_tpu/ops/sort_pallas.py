"""Pallas fused bitonic KV sort: the match scan's sort engine on TPU.

``jax.lax.sort`` is a general-purpose comparator sort; the LZ4 match scan
(ops/lz4_tpu.py) only ever sorts u32/i32 keys with one or two carried u32
values over power-of-two rows, and that shape admits a far cheaper program:
a bitonic merge network over the (rows, 128)-tiled VPU layout where every
compare-exchange is two ``pltpu.roll`` s + a select, entirely in VMEM
registers.  One kernel invocation fuses what XLA runs as separate HBM
round trips:

- ``match_deltas`` — the whole delta pipeline of the match scan: in-kernel
  key construction (hash16 << pos_bits | position; the _pos2_row interleave
  for stride 2), the hash-group bitonic sort, the neighbor compare
  (collision-exact, degenerate-gram exclusion, 65535 offset cap) fused
  between the merge networks, and the un-permute bitonic sort back to
  position order — one HBM read of the 4-gram image, one HBM write of the
  position-ordered deltas.
- ``sort_rows`` — the generic per-row KV sort used by the record pack
  sorts (L1/L2/L3 and the escape packs of the packed readback).

Network shape: element i of a row lives at tile (i // 128, i % 128); a
compare-exchange at stride j is a sublane roll (j >= 128) or a lane roll
(j < 128) pair selected by bit j of the index, so no stage gathers.
Unsigned key order is preserved by biasing u32 keys into i32 once at load
(x ^ 0x80000000) and unbiasing at store.  The network is unstable where
keys tie; every call site here either has unique keys (position-salted) or
ties only among don't-care slots (invalid-record padding), which is why
results are bit-identical to ``jax.lax.sort`` on the live data
(tests/test_sort_pallas.py asserts both properties).

Falls back to ``jax.lax.sort`` off-TPU (the 8-virtual-device CPU test
mesh), for sub-1024-entry rows (tile underflow), and under
``HDRF_SORT_PALLAS=0``; ``interpret=True`` runs the same kernel through the
Pallas interpreter so the CPU mesh can execute the network itself.

Re-expresses the sort stage the reference reaches through its JNI hash
table (DataDeduplicator.java:770-781 codec path) in the TPU-native
"sorting is the hash table" formulation (SURVEY.md; ops/lz4_tpu.py module
docstring).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_MIN_E = 1024          # below this the (R, 128) view loses whole-tile rows
_BIAS = np.uint32(0x80000000)
_HASH_MUL = np.uint32(2654435761)   # golden-ratio multiplier (lz4.cpp hash4)


def use_pallas() -> bool:
    """Trace-time gate: Mosaic kernels only on a real TPU backend (the
    test mesh is 8 virtual XLA:CPU devices), overridable for A/B timing."""
    if os.environ.get("HDRF_SORT_PALLAS", "1") == "0":
        return False
    return jax.default_backend() == "tpu"


def _to_i32(x):
    """Order-preserving reinterpret to i32 (u32 keys are biased so the
    network's signed compares realize unsigned order)."""
    if x.dtype == jnp.uint32:
        return jax.lax.bitcast_convert_type(x ^ _BIAS, jnp.int32)
    return x


def _from_i32(x, dtype):
    if dtype == jnp.uint32:
        return jax.lax.bitcast_convert_type(x, jnp.uint32) ^ _BIAS
    return x


def _bit(shape, b: int):
    """(i & b) != 0 over the flat index i = sublane*128 + lane of a
    (R, 128) tile, for a single-bit b.  Bits past the row range come out
    all-false, which is exactly the all-ascending final merge."""
    if b >= _LANES:
        return (jax.lax.broadcasted_iota(jnp.int32, shape, 0)
                & (b // _LANES)) != 0
    return (jax.lax.broadcasted_iota(jnp.int32, shape, 1) & b) != 0


def _partner(x, j: int):
    """x[i ^ j] for single-bit stride j: the two roll directions selected
    by bit j of the index (pltpu.roll(x, s, ax): out[i] = x[i - s])."""
    if j >= _LANES:
        jr = j // _LANES
        fwd = pltpu.roll(x, jr, 0)                    # x[r - jr]
        bwd = pltpu.roll(x, x.shape[0] - jr, 0)       # x[r + jr]
    else:
        fwd = pltpu.roll(x, j, 1)
        bwd = pltpu.roll(x, _LANES - j, 1)
    return jnp.where(_bit(x.shape, j), fwd, bwd)


def _network(key, vals, e: int):
    """The bitonic merge network over one (R, 128) row of e = R*128
    entries.  i32 key, i32 values; ascending.  Equal-key pairs never
    exchange (both sides keep their own KV), so ties stay in place."""
    for kk in range(1, e.bit_length()):
        k = 1 << kk
        j = k >> 1
        while j:
            pk = _partner(key, j)
            pvs = [_partner(v, j) for v in vals]
            # want_max = ascending XOR low-slot; low-slot = bit j clear.
            want_max = jnp.logical_xor(~_bit(key.shape, k),
                                       ~_bit(key.shape, j))
            take = jnp.where(want_max, pk > key, pk < key)
            key = jnp.where(take, pk, key)
            vals = [jnp.where(take, pv, v) for pv, v in zip(pvs, vals)]
            j >>= 1
    return key, vals


# ---------------------------------------------------------------- sort_rows


@functools.cache
def _sort_rows_call(e: int, n_val: int, key_unsigned: bool, interpret: bool):
    r = e // _LANES
    sign = np.int32(-2**31)       # bias on raw i32 bits == u32 ^ 0x80000000

    def kernel(*refs):
        key = refs[0][0]
        if key_unsigned:
            key = key ^ sign
        vals = [refs[1 + i][0] for i in range(n_val)]
        key, vals = _network(key, vals, e)
        if key_unsigned:
            key = key ^ sign
        refs[1 + n_val][0] = key
        for i in range(n_val):
            refs[2 + n_val + i][0] = vals[i]

    spec = pl.BlockSpec((1, r, _LANES), lambda i: (i, 0, 0))

    def call(key, *vals):
        t = key.shape[0]
        outs = pl.pallas_call(
            kernel,
            grid=(t,),
            in_specs=[spec] * (1 + n_val),
            out_specs=[spec] * (1 + n_val),
            out_shape=[jax.ShapeDtypeStruct((t, r, _LANES), jnp.int32)
                       ] * (1 + n_val),
            interpret=interpret,
        )(_i32_tiles(key, r), *[_i32_tiles(v, r) for v in vals])
        sk = jax.lax.bitcast_convert_type(outs[0], key.dtype).reshape(t, e)
        svs = [jax.lax.bitcast_convert_type(o, v.dtype).reshape(t, e)
               for o, v in zip(outs[1:], vals)]
        return (sk, *svs)

    return call


def _i32_tiles(x, r: int):
    """(t, e) -> (t, R, 128) i32 (raw bitcast; key bias happens in-kernel
    so padding constants supplied by callers keep their u32 meaning)."""
    x = jax.lax.bitcast_convert_type(x, jnp.int32)
    return x.reshape(x.shape[0], r, _LANES)


def _pow2_pad(key, vals, pad_key, pad_vals):
    """Pad rows to the next power of two so the network applies; pad keys
    must sort at or past every live key (callers pass their sentinel)."""
    e = key.shape[1]
    ep = 1 << (e - 1).bit_length()
    if ep == e:
        return key, vals
    ext = ((0, 0), (0, ep - e))
    key = jnp.pad(key, ext, constant_values=pad_key)
    vals = [jnp.pad(v, ext, constant_values=pv)
            for v, pv in zip(vals, pad_vals)]
    return key, vals


def sort_rows(key, *vals, impl: str | None = None, interpret: bool = False,
              pad_key=None, pad_vals=None):
    """Per-row ascending KV sort of (t, e) arrays (e along dimension 1):
    the drop-in for ``jax.lax.sort((key, *vals), dimension=1, num_keys=1)``
    at the match scan's call sites.  i32 or u32 key; i32/u32 values ride
    the same permutation.  Non-power-of-two rows are padded with
    ``pad_key``/``pad_vals`` (required then: the pad must be the caller's
    end-of-row sentinel) and the padded tail is returned too, so output
    width is the padded width only when e was already a power of two —
    callers that slice prefixes are unaffected.
    """
    if impl is None:
        impl = "pallas" if (use_pallas() or interpret) else "xla"
    e = key.shape[1]
    if impl != "pallas" or e < _MIN_E:
        return jax.lax.sort((key, *vals), dimension=1, num_keys=1)
    if e & (e - 1):
        assert pad_key is not None, "non-pow2 rows need a pad sentinel"
        key, vals = _pow2_pad(key, list(vals), pad_key, pad_vals)
        e = key.shape[1]
    return _sort_rows_call(e, len(vals), key.dtype == jnp.uint32,
                           interpret)(key, *vals)


# ------------------------------------------------------------- match_deltas


def _prev1(x, fill, shape):
    """Flat shift-right-by-one over the (R, 128) view: out[i] = x[i-1],
    out[0] = fill — the sorted-order left neighbor for the match compare."""
    lane = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    row = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    lr = pltpu.roll(x, 1, 1)                 # x[r, c-1]; wrong at c == 0
    rr = pltpu.roll(lr, 1, 0)                # x[r-1, 127] lands at c == 0
    out = jnp.where(lane == 0, rr, lr)
    return jnp.where((lane == 0) & (row == 0), fill, out)


@functools.cache
def _match_deltas_call(e: int, stride: int, pos_bits: int, interpret: bool):
    r = e // _LANES
    pmask = np.uint32((1 << pos_bits) - 1)

    def kernel(v_ref, d_ref):
        shape = (r, _LANES)
        v = jax.lax.bitcast_convert_type(v_ref[0], jnp.uint32)
        lane = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        row = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
        idx = row * _LANES + lane
        if stride == 2:                       # _pos2_row: [0,2,4...,1,3,5...]
            half = e // 2
            posn = jnp.where(idx < half, 2 * idx, 2 * (idx - half) + 1)
        else:
            posn = idx
        posn = posn.astype(jnp.uint32)
        h = (v * _HASH_MUL) >> jnp.uint32(32 - 16)
        key = (h << jnp.uint32(pos_bits)) | posn

        sk, (sv,) = _network(
            _to_i32(key), [jax.lax.bitcast_convert_type(v, jnp.int32)], e)
        sk = _from_i32(sk, jnp.uint32)
        sv = jax.lax.bitcast_convert_type(sv, jnp.uint32)

        # Neighbor compare, fused between the two merge networks (exact
        # collision rejection via the carried 4-gram; degenerate-gram and
        # offset-cap rules identical to the XLA reference below).
        pk = _prev1(sk, jnp.uint32(0xFFFFFFFF), shape)
        pv = _prev1(sv, jnp.uint32(0), shape)
        same = (sk >> jnp.uint32(pos_bits)) == (pk >> jnp.uint32(pos_bits))
        nondegen = sv != ((sv << jnp.uint32(8)) | (sv >> jnp.uint32(24)))
        okm = same & (sv == pv) & nondegen
        delta = jnp.where(okm,
                          ((sk & pmask) - (pk & pmask)) * jnp.uint32(stride),
                          jnp.uint32(0))
        delta = jnp.where(delta <= jnp.uint32(65535), delta, jnp.uint32(0))

        # Un-permute to position order (pos keys unique per row; they fit
        # i32 directly, but the shared bias path keeps one compare form).
        _, (d,) = _network(
            _to_i32(sk & pmask),
            [jax.lax.bitcast_convert_type(delta, jnp.int32)], e)
        d_ref[0] = d

    spec = pl.BlockSpec((1, r, _LANES), lambda i: (i, 0, 0))

    def call(vals):
        t = vals.shape[0]
        out = pl.pallas_call(
            kernel,
            grid=(t,),
            in_specs=[spec],
            out_specs=spec,
            out_shape=jax.ShapeDtypeStruct((t, r, _LANES), jnp.int32),
            interpret=interpret,
        )(_i32_tiles(vals, r))
        return jax.lax.bitcast_convert_type(out, jnp.uint32).reshape(t, e)

    return call


def match_deltas_xla(vals, posn, stride: int, pos_bits: int):
    """The XLA reference pipeline: hash-group ``lax.sort``, neighbor
    compare, un-permute ``lax.sort`` — the original ops/lz4_tpu.py:228-261
    formulation, kept verbatim as the CPU-mesh path and the kernel's
    bit-identity oracle."""
    t = vals.shape[0]
    h = (vals * _HASH_MUL) >> jnp.uint32(32 - 16)
    key = (h << jnp.uint32(pos_bits)) | posn
    sk, sv = jax.lax.sort((key, vals), dimension=1, num_keys=1)
    pk = jnp.concatenate([jnp.full((t, 1), 0xFFFFFFFF, jnp.uint32),
                          sk[:, :-1]], axis=1)
    pv = jnp.concatenate([jnp.zeros((t, 1), jnp.uint32), sv[:, :-1]], axis=1)
    same = (sk >> jnp.uint32(pos_bits)) == (pk >> jnp.uint32(pos_bits))
    nondegen = sv != ((sv << jnp.uint32(8)) | (sv >> jnp.uint32(24)))
    okm = same & (sv == pv) & nondegen
    pmask = jnp.uint32((1 << pos_bits) - 1)
    delta = jnp.where(okm, ((sk & pmask) - (pk & pmask)) * jnp.uint32(stride),
                      jnp.uint32(0))
    delta = jnp.where(delta <= jnp.uint32(65535), delta, jnp.uint32(0))
    _, d = jax.lax.sort((sk & pmask, delta), dimension=1, num_keys=1)
    return d


def match_deltas(vals, posn, stride: int, pos_bits: int,
                 impl: str | None = None, interpret: bool = False):
    """(t, e) u32 4-gram entries -> (t, e) u32 deltas in position order:
    stages 2-3 of the match scan as ONE device op.  ``posn`` is the entry
    position map (only the XLA path consumes it; the kernel rebuilds it
    from the flat index).  Both paths produce bit-identical deltas: sort
    keys are position-salted, hence unique, hence permutation-unique."""
    if impl is None:
        impl = "pallas" if (use_pallas() or interpret) else "xla"
    e = vals.shape[1]
    if impl != "pallas" or e < _MIN_E or e & (e - 1):
        return match_deltas_xla(vals, posn, stride, pos_bits)
    return _match_deltas_call(e, stride, pos_bits, interpret)(vals)
