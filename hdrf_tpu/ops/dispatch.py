"""Backend dispatch for the reduction hot ops (CDC scan + fingerprinting).

The reference hardwires its hot loops: the CDC byte scan is a sequential Java
loop (DataDeduplicator.chunking(), DataDeduplicator.java:264-307) and hashing
goes through JNI to libnayuki (utilities.java:98-137).  Here both ops have two
interchangeable backends with identical outputs (asserted in tests/test_ops.py):

- ``native``: C++ via ctypes (hdrf_tpu/native) — the CPU baseline the >=4x
  BASELINE target is measured against, and the correctness oracle.
- ``tpu``:    JAX/XLA device programs (hdrf_tpu/ops/gear.py, sha256.py) — the
  all-position Gear candidate scan and lane-parallel SHA-256.

``auto`` resolves to ``tpu`` when an accelerator is attached.
"""

from __future__ import annotations

import threading

import numpy as np

from hdrf_tpu.config import CdcConfig
from hdrf_tpu.utils import metrics as _metrics
from hdrf_tpu.utils import profiler as _profiler

# Op-level accounting at the dispatch boundary (per-dispatch device
# accounting lives in utils/device_ledger.py, fed by the ops modules).
_M = _metrics.registry("ops_dispatch")


def resolve_backend(backend: str) -> str:
    if backend != "auto":
        return backend
    try:
        import jax

        if any(d.platform == "tpu" for d in jax.devices()):
            return "tpu"
    except Exception:
        pass
    return "native"


def gear_mask(cdc: CdcConfig) -> int:
    """Boundary mask with ``mask_bits`` effective bits -> avg chunk 2^mask_bits.
    Bits are spread across the 32-bit hash (FastCDC observation: spread masks
    judge more of the window than low-contiguous ones)."""
    bits, mask, step = cdc.mask_bits, 0, 32 // max(cdc.mask_bits, 1)
    pos = 31
    for _ in range(bits):
        mask |= 1 << pos
        pos -= step
        if pos < 0:
            pos = 30
    return mask & 0xFFFFFFFF


def chunk_cuts(data: bytes | np.ndarray, cdc: CdcConfig,
               backend: str = "native") -> np.ndarray:
    """Exclusive chunk end offsets covering [0, len(data)]."""
    from hdrf_tpu import native

    mask = gear_mask(cdc)
    if backend == "tpu":
        from hdrf_tpu.ops import gear

        return gear.cdc_chunk_jax(data, mask, cdc.min_chunk, cdc.max_chunk)
    return native.cdc_chunk(data, mask, cdc.min_chunk, cdc.max_chunk)


def fingerprints(data: bytes | np.ndarray, cuts: np.ndarray,
                 backend: str = "native") -> np.ndarray:
    """(n_chunks, 32) SHA-256 digests of the chunks delimited by ``cuts``."""
    if backend == "tpu":
        from hdrf_tpu.ops import sha256 as sha_tpu

        return sha_tpu.fingerprint_chunks(data, cuts)
    from hdrf_tpu import native

    starts = np.concatenate([[0], cuts[:-1]]).astype(np.uint64)
    lens = (cuts - starts).astype(np.uint64)
    return native.sha256_batch(data, starts, lens)


_resident_cache: dict = {}
_mesh_cache: list = []
_mesh_plane: list = []
_mesh_plane_mesh_cache: list = []
_mesh_reducer_cache: dict = {}


def set_mesh_plane(flag: bool) -> None:
    """Process-wide switch for the mesh-sharded reduction plane
    (parallel/sharded.MeshReducer).  Set by the datanode from
    ReductionConfig.mesh_plane; default falls back to HDRF_MESH_PLANE=1."""
    _mesh_plane[:] = [bool(flag)]


def mesh_plane_enabled() -> bool:
    if _mesh_plane:
        return _mesh_plane[0]
    import os

    return os.environ.get("HDRF_MESH_PLANE", "") == "1"


def _mesh_plane_mesh():
    """Flat ('data'=n, 'seq'=1) mesh over every attached device — the
    block-data-parallel layout of the mesh reduction plane (one block per
    lane, fingerprint space partitioned over 'data').  None below 2 devices:
    the serial ResidentReducer is strictly better there."""
    if not _mesh_plane_mesh_cache:
        import jax

        from hdrf_tpu.parallel.sharded import make_mesh

        devs = jax.devices()
        _mesh_plane_mesh_cache.append(
            make_mesh(n_data=len(devs), n_seq=1, devices=devs)
            if len(devs) > 1 else None)
    return _mesh_plane_mesh_cache[0]


def mesh_reducer(cdc: CdcConfig, lanes_per_device: int = 2,
                 bucket_slots: int = 1 << 15):
    """Shared parallel/sharded.MeshReducer for this CDC geometry, or None
    when fewer than 2 devices are attached.  Shared (not per-pipeline) so
    the device bucket table sees every ChunkIndex commit exactly once and
    the jitted mesh-step programs are built once per geometry."""
    mesh = _mesh_plane_mesh()
    if mesh is None:
        return None
    key = (cdc.mask_bits, cdc.min_chunk, cdc.max_chunk,
           int(lanes_per_device), int(bucket_slots))
    r = _mesh_reducer_cache.get(key)
    if r is None:
        from hdrf_tpu.parallel.sharded import MeshReducer

        r = _mesh_reducer_cache[key] = MeshReducer(
            cdc, mesh=mesh, lanes_per_device=lanes_per_device,
            bucket_slots=bucket_slots)
    return r


def _multichip_mesh():
    """The flat ('data'=1, 'seq'=n) mesh over every attached device, built
    once — the serving path's multi-chip form engages automatically when
    more than one device is present."""
    if not _mesh_cache:
        import jax

        from hdrf_tpu.parallel.sharded import make_mesh

        devs = jax.devices()
        _mesh_cache.append(make_mesh(n_data=1, n_seq=len(devs),
                                     devices=devs)
                           if len(devs) > 1 else None)
    return _mesh_cache[0]


def chunk_and_fingerprint(data: bytes | np.ndarray, cdc: CdcConfig,
                          backend: str = "native"):
    """(cuts, digests) in one call — THE entry point for the write path.

    On the TPU backend this routes through ops.resident.ResidentReducer so
    the block crosses to HBM once and the gather/SHA read the resident image
    (the naive chunk_cuts+fingerprints composition re-uploads the block per
    stage).  With MULTIPLE devices attached, the block instead runs the
    sharded pipeline (parallel/sharded.reduce_sharded): seq-parallel
    candidate scan with ICI halo exchange + chunk-parallel SHA lanes over
    every chip.  The native path is the CPU baseline pair of calls.
    """
    from hdrf_tpu.reduction import accounting

    nbytes = len(data) if isinstance(data, (bytes, bytearray)) else data.nbytes
    _M.incr(f"reduce_{backend}_total")
    _M.incr(f"reduce_{backend}_bytes", nbytes)
    # Effective-geometry gauges: under the adaptive controller the cdc
    # object mutates between calls, and this is the one funnel every
    # reduction passes through.
    accounting.note_geometry(cdc)
    if backend == "tpu":
        mesh = _multichip_mesh()
        if mesh is not None:
            from hdrf_tpu.parallel.sharded import reduce_sharded

            return reduce_sharded(data, cdc, mesh)
        from hdrf_tpu.ops.cdc_pallas import cdc_pallas_mode, cdc_skip_ahead
        from hdrf_tpu.ops.resident import ResidentReducer

        # The fused-CDC mode and scan variant are part of the key: a
        # reducer pins both at construction (jit-cache coherence), so
        # flipping HDRF_CDC_PALLAS / HDRF_CDC_SKIP_AHEAD mid-process — or
        # an adaptive-controller retune mutating ``cdc`` — must select a
        # different reducer, not mutate one.
        key = (cdc.mask_bits, cdc.min_chunk, cdc.max_chunk,
               cdc_pallas_mode(), cdc_skip_ahead())
        r = _resident_cache.get(key)
        if r is None:
            r = _resident_cache[key] = ResidentReducer(
                cdc, fused_mode=key[3], skip_ahead=key[4])
        return r.reduce(data)
    # Native CDC+SHA run synchronously on the host, so they are a host
    # phase; the jax paths above must NOT be wrapped here — their wall time
    # is dominated by blocking device waits the ledger already attributes
    # as device_wait, and a host phase would misclassify that overlap.
    with _profiler.phase("reduce_compute"):
        cuts = chunk_cuts(data, cdc, backend)
        return cuts, fingerprints(data, cuts, backend)


_tpu_lz4 = None
_tpu_lz4_lock = threading.Lock()


def block_compress(codec: str, data: bytes, backend: str = "native") -> bytes:
    """Codec dispatch for the entropy stage (container seal / compress-only
    schemes).  ``lz4`` on the TPU backend runs match discovery on device
    (ops/lz4_tpu.py — the north star's compression kernel); every other
    codec/backend pair uses the host codec path.  Output is format-identical
    either way (standard LZ4 block), so readers never care who compressed."""
    global _tpu_lz4
    _M.incr(f"compress_{backend}_total")
    _M.incr(f"compress_{backend}_bytes", len(data))
    if codec == "lz4" and backend == "tpu":
        return _lz4_device().compress(data)
    from hdrf_tpu.utils import codec as codecs

    return codecs.compress(codec, data)


def _lz4_device():
    global _tpu_lz4
    if _tpu_lz4 is None:
        with _tpu_lz4_lock:
            if _tpu_lz4 is None:
                from hdrf_tpu.ops.lz4_tpu import TpuLz4

                _tpu_lz4 = TpuLz4()
    return _tpu_lz4


def block_decompress_batch(codec_names: list, blobs: list, usizes: list,
                           backend: str = "native") -> list:
    """Batched decode dispatch for the read coalescer
    (server/read_plane.py): one call decodes a whole coalesced window of
    sealed-container payloads.  LZ4 decode is byte-serial in its output
    dependence (ops/reconstruct.py:1-30), so the decode itself always runs
    the host oracle — the same one that verifies the TPU compressor's
    output (ops/lz4_tpu.py:63); this surface is the grouped DISPATCH seam,
    mirroring block_compress_batch's shape so per-window accounting lands
    in one place and a future device decoder slots in without touching
    callers."""
    _M.incr(f"decompress_{backend}_total", len(blobs))
    _M.incr(f"decompress_{backend}_bytes", sum(usizes))
    from hdrf_tpu.utils import codec as codecs

    return [codecs.decompress(c, b, u)
            for c, b, u in zip(codec_names, blobs, usizes)]


def block_compress_batch(codec: str, datas: list,
                         backend: str = "native") -> list:
    """Batched codec dispatch: equal-length lz4 payloads on the TPU backend
    run as ONE device program with one grouped record readback
    (TpuLz4.compress_many) — the transport-latency lever for multi-container
    seals, where per-container dispatch+readback round trips dominate.
    Everything else degrades to per-item block_compress."""
    if codec == "lz4" and backend == "tpu":
        _M.incr(f"compress_{backend}_total", len(datas))
        _M.incr(f"compress_{backend}_bytes", sum(len(d) for d in datas))
        if mesh_plane_enabled():
            mesh = _mesh_plane_mesh()
            if mesh is not None:
                from hdrf_tpu.parallel.sharded import (
                    lz4_compress_many_sharded,
                )

                return lz4_compress_many_sharded(_lz4_device(), datas, mesh)
        return _lz4_device().compress_many(datas)
    return [block_compress(codec, d, backend) for d in datas]
