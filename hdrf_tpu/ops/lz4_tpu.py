"""TPU LZ4 match discovery: the entropy stage of the reduction pipeline.

Re-expresses the reference's container/stream LZ4 compression
(DataDeduplicator.java:770-781 container rollover; BlockReceiver.java:822-866
stream codecs) as a device program.  The reference reaches LZ4 through JNI
(hadoop's native codec); here the expensive half of the encoder — match
discovery, which on CPU is a serial hash-table walk over every byte — runs on
TPU, and the cheap half — the greedy/lazy parse + byte serialization, which
is memcpy-bound — runs in native C++ (``hdrf_lz4_emit``).  This is the same
device/host split the CDC stage uses (device candidate scan, host cut select).

TPU-native formulation
----------------------
An LZ4 encoder needs, for every position p, the most recent previous position
with the same 4-byte prefix.  A hash table is the CPU answer; **sorting is
the TPU answer**: within a 128 KiB supertile, sort ``(hash16(w4) << 16) |
pos/2`` keys — the left neighbor of an entry in sorted order with an equal
hash is exactly the nearest previous occurrence.  Measured on one v5e chip,
tiled KV sort runs at ~3 ns/element while per-element gathers and scatters
(the hash-table formulation) scalarize at 300-600 ns/element — two orders of
magnitude; every stage here is therefore a dense op, a sort, or a scan, and
the design avoids gathers entirely:

1. BE u32 word image (shared MXU combine, ops/resident.be_word_image) +
   sliding 4-gram phases via funnel shifts; entries every ``stride`` bytes.
2. Per-supertile KV sort of (key=(hash<<16)|pos2, payload=w4); neighbor
   compare verifies true 4-byte equality (collisions rejected exactly).
3. A second per-supertile sort un-permutes to position order, where runs of
   consecutive positions with the same delta — one maximal match — reduce to
   shifted compares + a reverse-cummin run-length scan, and a cummax
   frontier keeps only records that advance coverage by >= 4 bytes (the
   order-free core of the greedy parse; without it, stride-offset chains of
   overlapping short matches flood ~n/stride records on RLE-ish data).
4. Gather-free record extraction: a pack sort moves kept records to row
   prefixes, a transpose rebalances them across rows (record density is
   wildly skewed — text regions emit 100x more than random regions), and a
   second small pack sort + static prefix slice yields a bounded readback.
   Slice widths are jit-shape hints learned from the workload; overflow is
   detected exactly (total vs returned) and retried wider.
5. One packed D2H, delta-encoded on device (the global pack sorts by
   position, so records arrive ascending): per record one u32 of
   (pos-delta hi8 | len9 | offset15) plus one u8 of pos-delta low bits —
   5 B/record against the naive (pos u32, delta<<16|len u32) 8 B — with
   two tiny escape lanes for the rare wide position gap (> 65535 entry
   units) or long run length (>= 511 units).  ``native.lz4_unpack_records``
   reconstructs the exact (pos, delta, len) triples on the host, so the
   emitted stream is byte-identical to the unpacked layout (which remains
   as the escape-overflow rescan shape).  O(sequences) either way — the
   irreducible cost of host-side serialization.

The two big per-supertile sorts and the whole delta pipeline between them
run as ONE Pallas kernel on TPU (ops/sort_pallas.match_deltas: in-kernel
key construction, fused neighbor compare, bitonic merge networks); the
record pack sorts ride the same kernel (sort_pallas.sort_rows).  The
``jax.lax.sort`` formulation is kept verbatim as the CPU-mesh fallback and
the kernels' bit-identity oracle.

The native emit re-verifies and exactly extends every record (the device's
run-based length estimate undershoots when a nearer duplicate interrupts a
run), choosing among records usable at the cursor by true extended end
(lazy matching).  **Round-trip correctness is independent of device
output** — only the ratio depends on it.  Output is standard LZ4 block
format, decoded by the same ``hdrf_lz4_decompress`` oracle as the CPU path.

Matching differences vs the byte-serial CPU encoder (ratio, not
correctness): match starts on ``stride``-aligned positions and offsets of
the same parity (the emit's backward extension recovers most unaligned
starts), window <= one supertile, sub-``min_len`` matches skipped.

Ratio policy (measured): structured data (code, logs) emits at or above the
serial encoder; degenerate RLE is excluded from the sort and recovered
exactly by the emit's constant-offset probes (zeros: identical ratio);
short-match-DENSE data (word-soup text, TeraGen rows at ~9 records per
100-byte row) exceeds the record-flood cap and falls back to the native
encoder outright — same encoder as the CPU scheme, within the segmented
path's junction-window loss (<0.02% measured, see _SEG) — and an adaptive
bypass skips the pointless scans once a stream shows its character.
Grey-zone containers additionally race the native encoder (decided on a
mid-container sample; full race when the sample is close) and keep the
smaller stream.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import threading
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np

from hdrf_tpu.utils import device_ledger as _ledger
from hdrf_tpu.utils import metrics as _metrics

_M_FLOOD = _metrics.registry("lz4_tpu")

# Segment width for host-parallel native LZ4 (flood fallback / bypass).
# Segments compress independently on a thread pool, then lz4_stitch merges
# them into ONE spec-valid LZ4 block stream (plain concatenation is NOT
# valid: the block format has no end marker, so each piece's final
# literals-only sequence would derail a decoder mid-stream).  Cost is only
# ratio: positions early in a segment lose their back-window (offsets never
# cross a junction) — LZ4's window is 64 KiB, so at 8 MiB segments <1% of
# positions are affected and on periodic data they re-match within their
# own segment; measured loss on a TeraGen container is <0.02%.
_SEG = 8 << 20


def _seq_head(lit_len: int, match_nibble: int) -> bytes:
    """Token + extended-length bytes for a sequence with ``lit_len``
    literals and the given low (match-length) nibble."""
    if lit_len < 15:
        return bytes([(lit_len << 4) | match_nibble])
    out = [0xF0 | match_nibble]
    rem = lit_len - 15
    while rem >= 255:
        out.append(255)
        rem -= 255
    out.append(rem)
    return bytes(out)


def lz4_stitch(pieces: list[tuple[bytes, int, int]]) -> bytes:
    """Merge independently compressed LZ4 block streams into one valid
    stream.  ``pieces`` are (stream, tail_token_off, tail_lit) from
    ``native.lz4_compress_tail``.  At each junction the left piece's final
    literals-only sequence is folded into the right piece's first sequence
    (lit runs concatenate; the match half is byte-identical, offsets being
    relative and segment-internal).  End-of-block restrictions hold because
    the final piece's tail is kept verbatim."""
    out = bytearray()
    pend_lits = b""   # literals awaiting the next sequence-with-a-match
    for stream, tail_off, tail_lit in pieces:
        body, tail = stream[:tail_off], stream[tail_off:]
        tail_literals = tail[-tail_lit:] if tail_lit else b""
        if body:
            if pend_lits:
                # fold pending literals into body's FIRST sequence
                t = body[0]
                lit = t >> 4
                p = 1
                if lit == 15:
                    while True:
                        b = body[p]
                        p += 1
                        lit += b
                        if b != 255:
                            break
                first_lits = body[p:p + lit]
                rest = body[p + lit:]   # offset+matchlen ext of seq 1 onward
                out += _seq_head(len(pend_lits) + lit, t & 0x0F)
                out += pend_lits
                out += first_lits
                out += rest
                pend_lits = b""
            else:
                out += body
            pend_lits = tail_literals
        else:
            # piece is a single literals-only sequence (tiny/incompressible
            # segment): just accumulate its literals
            pend_lits += tail_literals
    out += _seq_head(len(pend_lits), 0)
    out += pend_lits
    return bytes(out)


_POOL: ThreadPoolExecutor | None = None
_POOL_LOCK = threading.Lock()


def _pool() -> ThreadPoolExecutor:
    """Process-shared host-compression pool, created on first parallel use
    (a per-instance pool would leak 4 threads per TpuLz4 for the process
    lifetime; instances share one encoder workload anyway)."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = ThreadPoolExecutor(
                min(4, os.cpu_count() or 1), thread_name_prefix="lz4host")
        return _POOL


def _lz4_compress_parallel(a: np.ndarray) -> bytes:
    from hdrf_tpu import native

    # On a single-core host the segmented path only adds overhead (the
    # native calls release the GIL but there is no second core to use it);
    # the dev environment's DN hosts are 1-vCPU, real DN hosts are not.
    if a.size <= _SEG or (os.cpu_count() or 1) <= 1:
        return bytes(native.lz4_compress(a))
    parts = [a[o:o + _SEG] for o in range(0, a.size, _SEG)]
    return lz4_stitch(list(_pool().map(native.lz4_compress_tail, parts)))

_HASH_MUL = np.uint32(2654435761)  # golden-ratio multiplier (lz4.cpp hash4)
_S = 131072         # supertile span in bytes; window <= LZ4's 65535 anyway
_E3 = 8192          # L1 pack-sort row width (entries)
_L2R = 128          # balanced L2 rows
_BIG = 1 << 30
_INVALID = np.int32(2**31 - 1)


def _esc_slots(p3: int) -> int:
    """Escape-lane capacity of the packed record layout.  Sized so that a
    container would need one >64 KiB-entry-units position gap (or one
    >=511-unit match length) every 64 records to overflow — real corpora
    measure orders of magnitude below; overflow is detected exactly and
    falls back to a full-layout rescan."""
    return p3 // 64 + 64


def _packed_len(p3: int) -> int:
    """i32 words in a packed record row: [total, nv, esc1, esc2] header +
    A u32 per slot + one dpos low byte per slot (4 packed per word) + the
    two escape lanes."""
    return 4 + p3 + p3 // 4 + 2 * _esc_slots(p3)


@functools.cache
def _pos2_row(s4: int) -> np.ndarray:
    """Entry index -> pos/2 map for stride 2: [0,2,4,..., 1,3,5,...]."""
    return np.concatenate([2 * np.arange(s4, dtype=np.int32),
                           2 * np.arange(s4, dtype=np.int32) + 1])


def _match_scan_impl(block: jax.Array, stride: int, min_len: int,
                     p1: int, p2: int, p3: int, packed: bool = True):
    """u8[N] (N % _S == 0) -> i32 match-record row.

    ``packed=False`` (the full layout, also the escape-overflow rescan
    shape): i32[1 + 2*p3] of [total_kept, gpos x p3, (delta<<16|len) x p3];
    unused slots carry gpos == _INVALID; valid slots are position-ascending
    (the L3 pack sorts by gpos).

    ``packed=True``: i32[_packed_len(p3)] of [total_kept, n_valid,
    esc1_cnt, esc2_cnt] + A u32 x p3 + B u32 x p3/4 + E1 x esc_slots +
    E2 x esc_slots, where for record i (positions/deltas in entry units,
    i.e. divided by ``stride``):

      A[i] = delta_u (15 bits) | len9 (9 bits) << 15 | dpos_hi8 << 24
      B[i // 4] byte (i % 4)   = dpos_lo8
      dpos16 = pos_u[i] - pos_u[i-1]  (pos_u[-1] == 0); 0xFFFF escapes to
               E1 (absolute pos_u, record order)
      len9   = (mlen - 4) / stride, 32766 when mlen was clipped to 65535;
               >= 511 escapes to E2 (record order), stored as 511

    ~5 B/record against the full layout's 8, a ~36% smaller D2H row at the
    default p3.  The encoding is lossless for every represented record, so
    the host-reconstructed (pos, delta, len) triples — and therefore the
    emitted LZ4 stream — are byte-identical to the full layout's.

    In both layouts total_kept > valid slots means records were dropped by
    the p1/p2/p3 slices (caller may retry wider; a dropped record only
    costs ratio, never correctness).
    """
    from hdrf_tpu.ops import sort_pallas
    from hdrf_tpu.ops.resident import be_word_image

    n = block.shape[0]
    t = n // _S
    s4 = _S // 4
    w = be_word_image(block)
    if stride == 4:
        vals = w.reshape(t, s4)
        pos_bits = 15
        posn = jnp.broadcast_to(jnp.arange(s4, dtype=jnp.uint32), (t, s4))
    elif stride == 2:
        nxt = jnp.concatenate([w[1:], jnp.zeros(1, jnp.uint32)])
        mid = (w << 16) | (nxt >> 16)
        vals = jnp.concatenate([w.reshape(t, s4), mid.reshape(t, s4)], axis=1)
        pos_bits = 16
        posn = jnp.broadcast_to(
            jnp.asarray(_pos2_row(s4), dtype=jnp.uint32), (t, 2 * s4))
    else:
        raise ValueError("stride must be 2 or 4")

    # Sorts 1+2 and the neighbor compare between them: the hash-group sort
    # (the left neighbor of an entry in sorted order with an equal hash is
    # the nearest previous occurrence), the exact-equality/degenerate-gram/
    # offset-cap match rules, and the un-permute sort back to position
    # order, so entry i of a row is byte position stride*i and same-delta
    # runs are neighbor relations.  On TPU this is ONE Pallas kernel
    # (bitonic networks + fused compare, see ops/sort_pallas); off-TPU the
    # original lax.sort pipeline (match_deltas_xla) runs, bit-identically.
    d = sort_pallas.match_deltas(vals, posn, stride, pos_bits)

    okp = d > 0
    pd = jnp.concatenate([jnp.zeros((t, 1), jnp.uint32), d[:, :-1]], axis=1)
    cont = okp & (d == pd)
    start = okp & ~cont

    # Run length: distance to the next entry that breaks the run, via a
    # reverse cummin over (index where not-continuing, +inf elsewhere).
    e = d.shape[1]
    iota = jnp.broadcast_to(jnp.arange(e, dtype=jnp.int32), (t, e))
    pos_b = iota * stride
    brk = jnp.where(cont, _BIG, iota)
    nxt_brk = jax.lax.cummin(brk, axis=1, reverse=True)
    nxt1 = jnp.concatenate([nxt_brk[:, 1:], jnp.full((t, 1), e, jnp.int32)],
                           axis=1)
    run_entries = jnp.minimum(nxt1, e) - iota            # valid at starts
    mlen = (run_entries - 1) * stride + 4

    keep0 = start & (mlen >= min_len)
    # Frontier-advance filter: an order-free approximation of the greedy
    # parse.  The frontier is the furthest verified end so far; a record is
    # useful only if it reaches >= 4 bytes past it (enough for a legal match
    # tail after the parse consumes to the frontier).  A plain `end >
    # frontier` keeps stride-offset chains of overlapping short matches,
    # each advancing by `stride`; a `start >= frontier` cursor rule
    # over-suppresses (tail-extension records are what the parse uses —
    # dropping them measured ~30-90% ratio loss on text/code).
    end = pos_b + mlen
    fr = jax.lax.cummax(jnp.where(keep0, end, 0), axis=1)
    fr_before = jnp.concatenate([jnp.zeros((t, 1), jnp.int32), fr[:, :-1]],
                                axis=1)
    keep = keep0 & (end >= fr_before + 4)

    gpos = pos_b + jnp.arange(t, dtype=jnp.int32)[:, None] * _S
    rec = (d << jnp.uint32(16)) | jnp.minimum(mlen, 65535).astype(jnp.uint32)
    rec = jax.lax.bitcast_convert_type(rec, jnp.int32)
    total = jnp.sum(keep.astype(jnp.int32))

    # Gather-free extraction (TPU gathers scalarize at ~0.3-0.6 us/element;
    # a jnp.nonzero + take compaction measured ~0.7 s per 64 MiB — more than
    # the two KV sorts above combined).  Pack sort L1 moves kept records to
    # row prefixes; a transpose deals rows round-robin so the wildly skewed
    # record density (text supertiles emit 100x more than random ones)
    # balances before the L2 pack + static prefix slice.
    t3 = gpos.size // _E3
    l_iota = jnp.broadcast_to(jnp.arange(_E3, dtype=jnp.int32), (t3, _E3))
    k3 = jnp.where(keep.reshape(t3, _E3), l_iota, jnp.int32(_E3))
    g3 = jnp.where(keep.reshape(t3, _E3), gpos.reshape(t3, _E3), _INVALID)
    _, g1, r1 = sort_pallas.sort_rows(k3, g3, rec.reshape(t3, _E3))
    g1, r1 = g1[:, :p1], r1[:, :p1]                      # L1 prefix slice
    e2 = p1 * t3 // _L2R
    g2 = g1.T.reshape(_L2R, e2)
    r2 = r1.T.reshape(_L2R, e2)
    i2 = jnp.broadcast_to(jnp.arange(e2, dtype=jnp.int32), (_L2R, e2))
    k2 = jnp.where(g2 != _INVALID, i2, jnp.int32(e2))
    _, go, ro = sort_pallas.sort_rows(k2, g2, r2, pad_key=_INVALID,
                                      pad_vals=(_INVALID, np.int32(0)))
    go, ro = go[:, :p2], ro[:, :p2]                      # L2 prefix slice
    # L3 global pack: flatten and compact across rows so the D2H slice is
    # sized by the ACTUAL record count (p3), not by the per-row worst case
    # (_L2R * p2) — the padded readback measured 2-8 MB/container on this
    # corpus against ~1.5 MB of true records, and each extra D2H megabyte
    # costs real wall time on latency-bound transports.  Keyed on gpos
    # itself (valid positions are globally unique; _INVALID is the i32 max
    # so dead slots sort last on their own), which both drops a carried
    # value from the sort and lands records position-ascending — the order
    # the emit needs and the delta encoding below requires.
    gf, rf = go.reshape(-1), ro.reshape(-1)
    g4, r4 = sort_pallas.sort_rows(gf[None], rf[None], pad_key=_INVALID,
                                   pad_vals=(np.int32(0),))
    g4, r4 = g4[0, :p3], r4[0, :p3]                      # L3 prefix slice
    if not packed:
        return jnp.concatenate([total[None], g4, r4])

    # Packed readback encode (layout in the docstring).  All record fields
    # are stride multiples, so positions/deltas/lengths pack in entry units.
    valid = g4 != _INVALID
    nv = jnp.sum(valid.astype(jnp.int32))
    pos_u = jnp.where(valid, g4, 0) // stride
    prev = jnp.concatenate([jnp.zeros(1, jnp.int32), pos_u[:-1]])
    dpos = jnp.where(valid, pos_u - prev, 0)   # >= 0: ascending valid prefix
    esc1 = valid & (dpos >= 0xFFFF)
    dpos16 = jnp.where(esc1, 0xFFFF, dpos).astype(jnp.uint32)
    ru = jax.lax.bitcast_convert_type(r4, jnp.uint32)
    delta_u = (ru >> jnp.uint32(16)) // jnp.uint32(stride)
    mlen = ru & jnp.uint32(0xFFFF)
    # 65535 is the clip value, never a natural length (natural lengths are
    # == 4 mod stride), so the sentinel is unambiguous and reversible.
    len_u = jnp.where(mlen == jnp.uint32(65535), jnp.uint32(32766),
                      (mlen - jnp.uint32(4)) // jnp.uint32(stride))
    esc2 = valid & (len_u >= jnp.uint32(511))
    l9 = jnp.where(esc2, jnp.uint32(511), len_u)
    a_w = jnp.where(valid,
                    delta_u | (l9 << jnp.uint32(15))
                    | ((dpos16 >> jnp.uint32(8)) << jnp.uint32(24)),
                    jnp.uint32(0))
    blo = jnp.where(valid, dpos16 & jnp.uint32(0xFF), jnp.uint32(0))
    b4 = blo.reshape(-1, 4)
    b_w = (b4[:, 0] | (b4[:, 1] << jnp.uint32(8))
           | (b4[:, 2] << jnp.uint32(16)) | (b4[:, 3] << jnp.uint32(24)))
    # Escape lanes: pack-sort escaped records' absolute values to a static
    # prefix, in record order (the key is the record slot index).
    es = _esc_slots(p3)
    i4 = jnp.arange(p3, dtype=jnp.int32)
    k_e1 = jnp.where(esc1, i4, jnp.int32(p3))
    k_e2 = jnp.where(esc2, i4, jnp.int32(p3))
    v_e1 = jnp.where(esc1, pos_u, 0)
    v_e2 = jnp.where(esc2, len_u.astype(jnp.int32), 0)
    _, e1v = sort_pallas.sort_rows(k_e1[None], v_e1[None], pad_key=_INVALID,
                                   pad_vals=(np.int32(0),))
    _, e2v = sort_pallas.sort_rows(k_e2[None], v_e2[None], pad_key=_INVALID,
                                   pad_vals=(np.int32(0),))
    hdr = jnp.stack([total, nv,
                     jnp.sum(esc1.astype(jnp.int32)),
                     jnp.sum(esc2.astype(jnp.int32))])
    return jnp.concatenate([
        hdr,
        jax.lax.bitcast_convert_type(a_w, jnp.int32),
        jax.lax.bitcast_convert_type(b_w, jnp.int32),
        e1v[0, :es], e2v[0, :es],
    ])


_match_scan = functools.partial(
    jax.jit,
    static_argnames=("stride", "min_len", "p1", "p2", "p3", "packed"))(
        _match_scan_impl)


@functools.partial(
    jax.jit,
    static_argnames=("stride", "min_len", "p1", "p2", "p3", "packed"))
def _match_scan_batch(blocks: jax.Array, stride: int, min_len: int,
                      p1: int, p2: int, p3: int, packed: bool = True):
    """K equal-length blocks in ONE device program (one dispatch, one packed
    readback for the group) — same batching rationale as _prep_batch."""
    return jnp.stack([_match_scan_impl(blocks[k], stride, min_len, p1, p2,
                                       p3, packed)
                      for k in range(blocks.shape[0])])


@dataclasses.dataclass
class Lz4Job:
    n: int                     # true byte length
    host: np.ndarray           # host copy for emit/fallback
    block: jax.Array | None    # resident padded u8 (kept for overflow retry)
    recs: jax.Array | None     # packed records, D2H in flight
    p1: int = 0
    p2: int = 0
    p3: int = 0
    ev: object = None          # ledger token: scan dispatch -> rec readback


class TpuLz4:
    """Async LZ4 front end over the device match scan.

    Usage (overlapped): ``jobs = [c.submit(b) for b in bufs]`` then
    ``[c.finish(j) for j in jobs]`` — readbacks of job k hide under the
    dispatches of k+1.  ``compress`` is the synchronous convenience.  Inputs
    smaller than ``min_device`` bytes take the native path (device overhead
    beats the win below a couple of supertiles).
    """

    def __init__(self, stride: int = 2, min_len: int = 4,
                 min_device: int = 2 * _S):
        assert stride in (2, 4)
        self.stride = stride
        self.min_len = min_len
        self.min_device = min_device
        # Slice widths are jit-cache keys; blocks in one stream compress
        # alike, so sizes learned from overflow retries stick.  The lock
        # covers the hint state: concurrent seals (DataNode container lanes)
        # share one instance.
        self._p1 = 512
        self._p2 = 4096
        self._p3 = 1 << 17  # L3 packed-record slots (the D2H width)
        # Workload-adaptive flood bypass: after BYPASS_AFTER consecutive
        # flood fallbacks, the next BYPASS_RUN submits skip the device scan
        # entirely (a flooding stream — e.g. a TeraGen ingest — would
        # otherwise pay a wasted dispatch+readback per container), then one
        # probing scan re-checks whether the stream changed character.
        self._flood_streak = 0
        self._bypass_left = 0
        self.BYPASS_AFTER = 2
        self.BYPASS_RUN = 16
        self._lock = threading.Lock()

    def _pad(self, a: np.ndarray) -> np.ndarray:
        pad = (-a.size) % _S
        return np.concatenate([a, np.zeros(pad, np.uint8)]) if pad else a

    def _shapes(self, n_pad: int) -> tuple[int, int, int]:
        entries = n_pad // self.stride
        t3 = entries // _E3
        p1 = min(self._p1, _E3)
        while p1 * t3 % _L2R and p1 < _E3:
            p1 *= 2
        # _E3 is a multiple of _L2R, so the cap always divides evenly
        p2 = min(self._p2, p1 * t3 // _L2R)
        p3 = min(self._p3, _L2R * p2)
        return p1, p2, p3

    def submit(self, data: bytes | np.ndarray,
               device_image: jax.Array | None = None) -> Lz4Job:
        """``device_image`` (padded u8, length % _S == 0) skips the host->
        device upload when the bytes are already HBM-resident — the
        co-located TPU-worker deployment, where container payloads were
        staged during reduction (and the bench's service-rate framing)."""
        a = (np.frombuffer(data, dtype=np.uint8)
             if not isinstance(data, np.ndarray) else data)
        if a.size < self.min_device:
            return Lz4Job(n=a.size, host=a, block=None, recs=None)
        with self._lock:
            if self._bypass_left > 0:
                self._bypass_left -= 1
                _M_FLOOD.incr("bypassed_scans")
                return Lz4Job(n=a.size, host=a, block=None, recs=None)
        if device_image is not None:
            assert device_image.shape[0] % _S == 0
            block = device_image
        else:
            block = jax.device_put(self._pad(a))
        p1, p2, p3 = self._shapes(block.shape[0])
        ev = _ledger.dispatch(
            "lz4.scan",
            h2d_bytes=0 if device_image is not None else block.shape[0],
            key=(block.shape[0], p1, p2, p3))
        recs = _match_scan(block, self.stride, self.min_len, p1, p2, p3)
        recs.copy_to_host_async()
        return Lz4Job(n=a.size, host=a, block=block, recs=recs, p1=p1, p2=p2,
                      p3=p3, ev=ev)

    def _unpack_full(self, rec_row: np.ndarray, p3: int):
        total = int(rec_row[0])
        g = rec_row[1:1 + p3]
        r = rec_row[1 + p3:]
        m = g != _INVALID
        g, r = g[m], r[m]
        # The L3 pack sorts by gpos, so records already arrive ascending;
        # the stable argsort is then the identity and stays as a guard only
        # on this rare path (escape-overflow rescans).
        order = np.argsort(g, kind="stable")
        return total, g[order], r[order].view(np.uint32)

    def _unpack_packed(self, rec_row: np.ndarray, p3: int):
        from hdrf_tpu import native

        total, nv = int(rec_row[0]), int(rec_row[1])
        e1, e2 = int(rec_row[2]), int(rec_row[3])
        es = _esc_slots(p3)
        g, r, nrec = native.lz4_unpack_records(
            np.ascontiguousarray(rec_row[4:]).view(np.uint32), p3, nv,
            self.stride, es)
        complete = e1 <= es and e2 <= es and nrec == nv
        return total, g[:nrec], r[:nrec], complete

    def _records(self, job: Lz4Job, rec_row: np.ndarray):
        """Decode one packed record row; escape-lane overflow (needs
        thousands of >64Ki-entry gaps or >=511-unit lengths in ONE
        container) rescans in the full layout for the exact record set."""
        total, g, r, complete = self._unpack_packed(rec_row, job.p3)
        if not complete and job.block is not None:
            _M_FLOOD.incr("escape_rescans")
            ev = _ledger.dispatch(
                "lz4.rescan",
                key=(job.block.shape[0], job.p1, job.p2, job.p3, "full"))
            row = np.asarray(_match_scan(job.block, self.stride,
                                         self.min_len, job.p1, job.p2,
                                         job.p3, packed=False))
            _ledger.readback(ev, d2h_bytes=row.nbytes)
            return self._unpack_full(row, job.p3)
        return total, g, r

    def _assemble(self, job: Lz4Job, rec_row: np.ndarray) -> bytes:
        from hdrf_tpu import native

        total, g, r = self._records(job, rec_row)
        # Slice overflow dropped records: jump every hint straight to the
        # size ``total`` demands (sticky — peers and later jobs reuse it),
        # then rescan ONCE per hint level; each full rescan costs a
        # dispatch + readback, so iterative doubling is the wrong shape.
        while total > g.size and job.block is not None:
            with self._lock:
                def pow2(v: int) -> int:
                    return 1 << int(max(v, 1) - 1).bit_length()

                need = pow2(total)
                e_cap = job.block.shape[0] // self.stride
                if need > max(e_cap // 64, 1 << 16):
                    # Record flood (> ~8k records/MiB ~= a sequence every
                    # <128 B): short-match-dense data is the serial
                    # hash-table encoder's home turf and the sort scan's
                    # worst case — the native encoder takes over (same
                    # encoder as the CPU scheme, within the segmented
                    # path's junction-window loss, see _SEG).
                    break
                t3 = max(e_cap // _E3, 1)
                self._p3 = max(self._p3, min(need, e_cap))
                if need > _L2R * self._shapes(job.block.shape[0])[1]:
                    # per-row L2 slots must cover the records too (skew
                    # headroom 2x), or the L3 pack starves
                    self._p2 = max(self._p2,
                                   min(pow2(2 * need // _L2R),
                                       e_cap // _L2R))
                if need > self._shapes(job.block.shape[0])[0] * t3:
                    # hints stay powers of two: _shapes' divisibility
                    # doubling must terminate at the _E3 cap
                    self._p1 = max(self._p1,
                                   min(_E3, pow2(2 * need // t3)))
                shapes = self._shapes(job.block.shape[0])
            if shapes == (job.p1, job.p2, job.p3):
                break  # capacity exhausted: dropped records cost only ratio
            p1, p2, p3 = shapes
            ev = _ledger.dispatch("lz4.rescan",
                                  key=(job.block.shape[0], p1, p2, p3))
            rec_row = np.asarray(_match_scan(
                job.block, self.stride, self.min_len, p1, p2, p3))
            _ledger.readback(ev, d2h_bytes=rec_row.nbytes)
            job.p1, job.p2, job.p3 = p1, p2, p3
            total, g, r = self._records(job, rec_row)
        if total > g.size:
            # Record flood the slices can't represent: short-match-dense
            # data (e.g. word-soup text needs a sequence every ~9 bytes) is
            # exactly where a serial hash-table encoder is the right tool —
            # fall back to it (ratio = CPU scheme's, within the segmented
            # path's junction-window loss) instead of emitting from an
            # arbitrary record subset.
            _M_FLOOD.incr("native_fallbacks")
            with self._lock:
                self._flood_streak += 1
                if self._flood_streak >= self.BYPASS_AFTER:
                    self._bypass_left = self.BYPASS_RUN
            return _lz4_compress_parallel(job.host)
        with self._lock:
            self._flood_streak = 0
        m = g < max(job.n - 12, 0)    # spec MFLIMIT; drops pad-region hits
        g, r = g[m], r[m]
        out = native.lz4_emit(job.host, g, r)
        if total > (job.n // self.stride) >> 10:
            # Grey zone (non-trivial record density below the flood cap):
            # the sorted matcher can trail the serial encoder by a few
            # percent here — race the native encoder and keep the smaller
            # stream.  The full-container race costs a whole native
            # compress per grey container (~0.3 s at 32 MiB — measured as
            # the second-largest TPU-path host cost on the mixed corpus),
            # so first DECIDE on a sample: both encoders compress the same
            # mid-container span, and only when the emit does not clearly
            # win there does the full race run.  The decision errs toward
            # racing (skip only on a >=2% sample win), so the kept stream
            # is the smaller one wherever the outcome is close.
            if self._sample_says_emit_wins(job, g, r, len(out)):
                _M_FLOOD.incr("races_skipped")
            else:
                alt = _lz4_compress_parallel(job.host)
                if len(alt) and len(alt) < len(out):
                    _M_FLOOD.incr("native_wins")
                    out = alt
        return out

    _RACE_SAMPLE = 4 << 20

    def _sample_says_emit_wins(self, job: Lz4Job, g: np.ndarray,
                               r: np.ndarray, out_len: int) -> bool:
        """True when the device-records emit beats the serial encoder by
        >=2% on a mid-container sample span (same bytes, same records,
        rebased) — the containers where racing the full native encoder
        would only reproduce a larger stream."""
        from hdrf_tpu import native

        n = job.n
        if n < 3 * self._RACE_SAMPLE or out_len >= n:
            return False  # small container or emit >= raw: race cheaply/properly
        lo = (n // 2) & ~65535
        lo0 = max(lo - 65536, 0)   # back-window so sampled offsets verify
        hi = min(lo + self._RACE_SAMPLE, n)
        sl = job.host[lo0:hi]
        m = (g >= lo0) & (g < hi - 12)
        es = native.lz4_emit(sl, g[m] - lo0, r[m])
        ns = native.lz4_compress(sl)
        return len(es) * 100 <= len(ns) * 98

    def finish(self, job: Lz4Job) -> bytes:
        if job.recs is None:
            return (_lz4_compress_parallel(job.host)
                    if job.n else b"")
        rows = np.asarray(job.recs)
        _ledger.readback(job.ev, d2h_bytes=rows.nbytes)
        job.ev = None
        out = self._assemble(job, rows)
        job.block = None
        job.recs = None
        return out

    def compress(self, data: bytes | np.ndarray) -> bytes:
        return self.finish(self.submit(data))

    # ------------------------------------------------------- batched groups

    def submit_many(self, datas: list, device_images: list | None = None):
        """A group of blocks runs as one device program with one grouped
        readback — the transport-latency lever (each separate readback
        costs a fixed round trip).  ``device_images`` supplies HBM-resident
        padded u8 arrays; when they share one shape the group runs batched
        regardless of the true byte lengths (the pad region's records are
        masked out by the emit's MFLIMIT cut).  Without images, host
        buffers must be equal-length to batch; otherwise per-buffer
        submits."""
        arrs = [np.frombuffer(d, dtype=np.uint8)
                if not isinstance(d, np.ndarray) else d for d in datas]
        with self._lock:
            if self._bypass_left >= len(arrs):
                self._bypass_left -= len(arrs)
                _M_FLOOD.incr("bypassed_scans", len(arrs))
                return [Lz4Job(n=a.size, host=a, block=None, recs=None)
                        for a in arrs]
        if device_images is not None:
            shapes = {img.shape[0] for img in device_images}
            if (len(shapes) == 1 and len(arrs) > 1
                    and min(a.size for a in arrs) >= self.min_device):
                blocks = jnp.stack(device_images)
                p1, p2, p3 = self._shapes(blocks.shape[1])
                ev = _ledger.dispatch(
                    "lz4.scan_batch", batch=len(arrs),
                    key=(len(arrs), blocks.shape[1], p1, p2, p3))
                recs = _match_scan_batch(blocks, self.stride, self.min_len,
                                         p1, p2, p3)
                recs.copy_to_host_async()
                return ([Lz4Job(n=a.size, host=a, block=blocks[k],
                                recs=None, p1=p1, p2=p2, p3=p3)
                         for k, a in enumerate(arrs)], recs, ev)
            return [self.submit(a, device_image=img)
                    for a, img in zip(arrs, device_images)]
        sizes = {a.size for a in arrs}
        if len(sizes) != 1 or arrs[0].size < self.min_device or len(arrs) == 1:
            return [self.submit(a) for a in arrs]
        n = arrs[0].size
        stacked = np.stack([self._pad(a) for a in arrs])
        blocks = jax.device_put(stacked)
        p1, p2, p3 = self._shapes(stacked.shape[1])
        ev = _ledger.dispatch(
            "lz4.scan_batch", batch=len(arrs), h2d_bytes=stacked.nbytes,
            key=(len(arrs), stacked.shape[1], p1, p2, p3))
        recs = _match_scan_batch(blocks, self.stride, self.min_len, p1, p2,
                                 p3)
        recs.copy_to_host_async()
        return ([Lz4Job(n=n, host=a, block=blocks[k], recs=None, p1=p1,
                        p2=p2, p3=p3)
                 for k, a in enumerate(arrs)], recs, ev)

    def finish_many(self, submitted) -> list[bytes]:
        if isinstance(submitted, list):  # per-buffer fallback shape
            return [self.finish(j) for j in submitted]
        jobs, recs, ev = submitted
        rows = np.asarray(recs)
        _ledger.readback(ev, d2h_bytes=rows.nbytes)
        return [self._assemble(j, rows[k]) for k, j in enumerate(jobs)]

    def compress_many(self, datas: list) -> list[bytes]:
        return self.finish_many(self.submit_many(datas))
