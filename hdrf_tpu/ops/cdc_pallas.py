"""Fused Pallas CDC front end: Gear scan + in-kernel min/max cut selection.

One kernel pass over the resident block replaces the three-stage XLA front
end of ops/resident.py (``_prep``'s MXU BE word image + gear scan + bitmap
pack, the packed-candidate D2H, and the host ``native.cdc_select`` round
trip re-expressing DataDeduplicator.java:264-307).  The kernel fuses, per
(R, 128)-word supertile of the raw block:

1. **Gear map** — ``G[b] = fmix32(b * 0x9E3779B1)`` computed arithmetically
   per byte *phase* of the little-endian u32 word image (native/src/cdc.cpp
   pre-tabulates the same function; a 256-entry gather scalarizes on TPU,
   PERF_NOTES.md round 2).
2. **Window-32 hash** — the log-doubling recurrence of ops/gear.py
   (``A_{2m}[i] = A_m[i] + (A_m[i-m] << m)``, gear.py:66-79) decomposed by
   byte phase: a window-4 cross-phase combine, then three per-phase
   doublings whose byte lags (4, 8, 16) are exact word lags (1, 2, 4) —
   every shift is a ``pltpu.roll`` flat word shift, with the previous
   supertile's last row carried in VMEM scratch so tile boundaries are
   seamless.
3. **Candidate mask** — ``(h & mask) == 0`` at positions
   ``gear.MIN_CANDIDATE_POS1 <= pos1 <= true_n`` (the shared window-warmup
   convention, gear.py:85-104), reduced to per-word candidate nibbles and a
   per-row first-candidate summary.  The skip-ahead variant (default)
   additionally masks the static min-size dead zone up front:
   ``pos1 < gear.skip_ahead_threshold(min_chunk)`` can never be selected
   (every window opens at ``prev+min``), so those candidates never reach
   the summaries — the SIMD-chunking min-skip of arXiv:2508.05797 mapped
   onto the 8x128 lane grid.
4. **Cut selection** — the frontier semantics of ``hdrf_cdc_select``
   (native/src/cdc.cpp:74-92: ``lo = start+min``,
   ``hi = min(start+max, len)``, first candidate in [lo, hi] else ``hi``).
   PR 4's scan walked the summaries word-by-word per cut
   (O(candidate words) SMEM trips).  The sequence-based select (the
   arXiv:2505.21194 two-phase trick, default on) instead reduces each
   supertile's per-word first-candidate array to VECTORIZED suffix-min
   summaries — within-row (lane log-doubling rolls) and cross-row over
   the two-slab window — so the per-cut walk collapses to O(1): one
   nibble resolve in ``lo``'s own word, one within-row suffix read, one
   cross-row suffix read.  Frontier/counters still carry across
   supertiles in SMEM scratch; cuts land in an on-device table; each
   chunk is binned (by padded SHA block count) into one of two
   device-resident offset/length lane tables that feed
   ``_bucket_sha_best`` (ops/resident.py) with **no host round trip** —
   the SHA dispatch enqueues before the cut table is ever read back.
   ``FusedPlan.skip_ahead`` statically selects the variant, so the PR 4
   scan remains compilable as the A/B baseline
   (``benchmarks cdc --no-skip-ahead``).

The kernel additionally emits the big-endian word image (in-kernel byteswap
of the LE words — the separate ``be_word_image`` MXU pass of
ops/resident.py:89-103 disappears from the fused path) and a header
``[n_cuts, overflow, n_small, n_big]``: a block whose candidate density
exceeds the static cut capacity sets ``overflow`` and the caller falls back
to the XLA prep + host-select oracle path — boundaries are never silently
truncated (tests/test_cdc_pallas.py pins this with a low-entropy corpus).

``HDRF_CDC_PALLAS=0`` disables the fused path; ``=interpret`` forces the
Pallas interpreter so the CPU test mesh executes the same kernel program
Mosaic compiles on a chip (the ops/sort_pallas.py:59-64 gate pattern).
"""

from __future__ import annotations

import dataclasses
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hdrf_tpu.ops import gear

WINDOW = gear.WINDOW
_GOLD = np.uint32(0x9E3779B1)
_INF = np.int32(0x7FFFFFFF)

# Header lanes at the front of the cut table readback.  H_SURV / H_CANDS
# are the sequence-select telemetry lanes (zero under the PR 4 scan):
# slab survivors = rows whose first-candidate summary is finite (the
# per-slab survivor list the two-phase select reduces the scan to),
# candidates = masked candidate population that survived the skip-ahead
# dead zone.
TABLE_HDR = 8
H_COUNT, H_OVERFLOW, H_SMALL, H_BIG = 0, 1, 2, 3
H_SURV, H_CANDS = 4, 5


def cdc_pallas_mode() -> str:
    """Trace-time gate: 'mosaic' on a real TPU backend, 'off' on the CPU
    mesh, overridable via HDRF_CDC_PALLAS (``0`` = off everywhere,
    ``interpret`` = run the kernel through the Pallas interpreter — the
    tier-1 path that executes the same program Mosaic compiles)."""
    env = os.environ.get("HDRF_CDC_PALLAS", "")
    if env == "0":
        return "off"
    if env == "interpret":
        return "interpret"
    if jax.default_backend() == "tpu":
        return "mosaic"
    if env == "1":  # forcing the fused path without a chip = interpreter
        return "interpret"
    return "off"


def cdc_skip_ahead() -> bool:
    """Static gate for the skip-ahead + sequence-select scan variant
    (ISSUE 15 tentpole; arXiv:2505.21194's two-phase select).  Default on;
    ``HDRF_CDC_SKIP_AHEAD=0`` pins the PR 4 sequential frontier scan — the
    A/B baseline ``benchmarks cdc`` sweeps.  Like ``cdc_pallas_mode`` it is
    resolved once per reducer construction (ops/resident.py:224) so a
    mid-process flip selects a different cached reducer instead of
    mutating one."""
    return os.environ.get("HDRF_CDC_SKIP_AHEAD", "1") != "0"


# --------------------------------------------------------------------------
# Static per-block plan (jit/pallas cache key material)
# --------------------------------------------------------------------------

def _r128(n: int) -> int:
    return max(128, -(-n // 128) * 128)


@dataclasses.dataclass(frozen=True)
class FusedPlan:
    """Static shape plan of one fused-CDC block invocation."""
    true_n: int      # unpadded byte length
    n_pad: int       # bytes padded to the supertile grid
    R: int           # supertile rows (x128 u32 words = R*512 bytes)
    T: int           # supertiles
    cap: int         # cut-table capacity (header-counted overflow past it)
    Ls: int          # small-bucket lane capacity (128-grid)
    Lb: int          # big-bucket lane capacity (128-grid)
    b_small: int     # small bucket width, 64-byte SHA blocks
    b_big: int       # big bucket width (max_chunk rounded), SHA blocks
    mask: int
    min_chunk: int
    max_chunk: int
    skip_ahead: bool = True   # sequence-select scan (False = PR 4 scan)


def plan_for(true_n: int, mask: int, mask_bits: int, min_chunk: int,
             max_chunk: int, b_small: int, b_big: int,
             skip_ahead: bool | None = None) -> FusedPlan:
    """Shape plan: supertile >= max_chunk so a chunk search window spans at
    most two tiles (the revisited two-slab scratch); cut capacity =
    min(hard bound n/min_chunk, ~2x the expected chunk count) — the
    distributional cap is what a pathological low-entropy block overflows
    into the XLA fallback.

    Under ``skip_ahead`` the distributional cap accounts for the min-size
    dead zone (the ISSUE 15 overflow-header fix): cuts renew at least
    ``min_chunk`` apart before the geometric candidate wait, so the
    expected count follows the renewal spacing ``min_chunk + 2^mask_bits``
    rather than the raw candidate density — never LOOSER than the PR 4
    cap, so every corpus that overflowed into the XLA fallback before
    (zeros at any controller-emitted geometry included) still does
    (regression-pinned at the controller's smallest min-size in
    tests/test_cdc_pallas.py)."""
    if skip_ahead is None:
        skip_ahead = cdc_skip_ahead()
    min_chunk = max(1, min_chunk)
    R = -(-max(65536, max_chunk) // 512)
    R = -(-R // 8) * 8
    B = R * 512
    n_pad = true_n + (-true_n) % B
    hard = true_n // min_chunk + 2
    if skip_ahead:
        spacing = min_chunk + (1 << min(max(mask_bits, 0), 30))
        distr = 2 * (true_n // spacing) + 1024
    else:
        distr = 2 * (true_n >> max(mask_bits, 0)) + 1024
    cap = max(2, min(hard, distr))
    bs = max(1, min(b_small, b_big))
    big_min_len = max(bs * 64 - 72, 1)
    Lb = _r128(min(cap, true_n // big_min_len + 1))
    return FusedPlan(true_n=true_n, n_pad=n_pad, R=R, T=n_pad // B,
                     cap=cap, Ls=_r128(cap), Lb=Lb, b_small=bs, b_big=b_big,
                     mask=mask & 0xFFFFFFFF, min_chunk=min_chunk,
                     max_chunk=max_chunk, skip_ahead=bool(skip_ahead))


# --------------------------------------------------------------------------
# Shared vector core: phase-decomposed gear hashes over one supertile
# --------------------------------------------------------------------------

def _fmix32v(z):
    z = z ^ (z >> np.uint32(16))
    z = z * np.uint32(0x85EBCA6B)
    z = z ^ (z >> np.uint32(13))
    z = z * np.uint32(0xC2B2AE35)
    return z ^ (z >> np.uint32(16))


def _shift_words(x, m: int, prev_row):
    """Row-major flat shift right by ``m`` words of a (R, 128) register
    array: out_flat[i] = x_flat[i - m], with lanes wrapping into the
    previous sublane row and row 0 fed from ``prev_row`` — the previous
    supertile's last row carried in scratch (zeros at stream start, which
    reproduces the zero-pad semantics of gear._doubling_hashes)."""
    R = x.shape[0]
    lane = jax.lax.broadcasted_iota(jnp.int32, (R, 128), 1)
    row = jax.lax.broadcasted_iota(jnp.int32, (R, 128), 0)
    x_l = pltpu.roll(x, m, 1)
    x_up = pltpu.roll(x, 1, 0)
    x_up = jnp.where(row == 0, jnp.broadcast_to(prev_row, (R, 128)), x_up)
    x_ul = pltpu.roll(x_up, m, 1)
    return jnp.where(lane < m, x_ul, x_l)


def _tile_hashes(w, hist_ref):
    """Window-32 gear hashes of one (R, 128) LE-word supertile, by phase.

    Returns (h0..h3) where h_p[r, l] is the hash ending at byte
    4*(128r + l) + p.  Reads the 16 carried last-rows (4 stages x 4 phases)
    from ``hist_ref`` and writes this tile's own before returning."""
    R = w.shape[0]
    b = [(w >> np.uint32(8 * p)) & np.uint32(0xFF) for p in range(4)]
    g = [_fmix32v(bp * _GOLD) for bp in b]
    gs = [None] + [_shift_words(g[p], 1, hist_ref[p:p + 1, :])
                   for p in (1, 2, 3)]
    u = np.uint32
    s4 = [g[0] + (gs[3] << u(1)) + (gs[2] << u(2)) + (gs[1] << u(3)),
          g[1] + (g[0] << u(1)) + (gs[3] << u(2)) + (gs[2] << u(3)),
          g[2] + (g[1] << u(1)) + (g[0] << u(2)) + (gs[3] << u(3)),
          g[3] + (g[2] << u(1)) + (g[1] << u(2)) + (g[0] << u(3))]
    a8 = [s4[p] + (_shift_words(s4[p], 1, hist_ref[4 + p:5 + p, :]) << u(4))
          for p in range(4)]
    a16 = [a8[p] + (_shift_words(a8[p], 2, hist_ref[8 + p:9 + p, :]) << u(8))
           for p in range(4)]
    h = [a16[p] + (_shift_words(a16[p], 4,
                                hist_ref[12 + p:13 + p, :]) << u(16))
         for p in range(4)]
    for p in range(4):
        hist_ref[p:p + 1, :] = g[p][R - 1:R, :]
        hist_ref[4 + p:5 + p, :] = s4[p][R - 1:R, :]
        hist_ref[8 + p:9 + p, :] = a8[p][R - 1:R, :]
        hist_ref[12 + p:13 + p, :] = a16[p][R - 1:R, :]
    return h


# --------------------------------------------------------------------------
# The fused select kernel
# --------------------------------------------------------------------------

def _select_kernel(w_ref, wbe_ref, table_ref, ols_ref, olb_ref,
                   cmask_ref, rfc_ref, *scratch, p: FusedPlan):
    if p.skip_ahead:
        wsx_ref, rsx_ref, hist_ref, st_ref = scratch
    else:
        hist_ref, st_ref = scratch
    R, cap, Ls, Lb = p.R, p.cap, p.Ls, p.Lb
    B = R * 512
    t = pl.program_id(0)
    T = pl.num_programs(0)
    i32 = jnp.int32

    @pl.when(t == 0)
    def _init():
        for i in range(8):
            st_ref[i] = 0
        hist_ref[...] = jnp.zeros_like(hist_ref)
        table_ref[...] = jnp.zeros_like(table_ref)
        ols_ref[...] = jnp.zeros_like(ols_ref)
        olb_ref[...] = jnp.zeros_like(olb_ref)
        cmask_ref[...] = jnp.zeros_like(cmask_ref)
        rfc_ref[...] = jnp.full_like(rfc_ref, _INF)
        if p.skip_ahead:
            wsx_ref[...] = jnp.full_like(wsx_ref, _INF)

    @pl.when(t > 0)
    def _slide():  # two-tile window: current tile -> slab 1, previous -> 0
        cmask_ref[0] = cmask_ref[1]
        rfc_ref[0] = rfc_ref[1]
        if p.skip_ahead:
            wsx_ref[0] = wsx_ref[1]

    w = w_ref[...]
    # In-kernel BE word image (replaces the separate MXU combine pass).
    u = np.uint32
    wbe_ref[...] = (((w & u(0xFF)) << u(24)) | ((w >> u(8) & u(0xFF)) << u(16))
                    | ((w >> u(16) & u(0xFF)) << u(8)) | (w >> u(24)))

    h = _tile_hashes(w, hist_ref)
    row = jax.lax.broadcasted_iota(i32, (R, 128), 0)
    lane = jax.lax.broadcasted_iota(i32, (R, 128), 1)
    word_g = t * (R * 128) + row * 128 + lane
    pos0 = word_g * 4 + 1                       # pos1 of phase 0
    mask = u(p.mask)
    # Skip-ahead dead zone: positions below gear.skip_ahead_threshold can
    # never be selected (every window opens at prev+min), so masking them
    # here is cut-identical and keeps dead candidates out of every summary
    # the select walks or jumps over.
    thr = (gear.skip_ahead_threshold(p.min_chunk) if p.skip_ahead
           else gear.MIN_CANDIDATE_POS1)
    cand, fc = [], jnp.full((R, 128), _INF, i32)
    for ph in range(4):
        pos = pos0 + ph
        c = ((h[ph] & mask) == 0) & (pos >= thr) & (pos <= p.true_n)
        cand.append(c.astype(i32))
        fc = jnp.minimum(fc, jnp.where(c, pos, _INF))
    cmask_ref[1] = (cand[0] | (cand[1] << 1) | (cand[2] << 2)
                    | (cand[3] << 3))
    row_min = jnp.min(fc, axis=1, keepdims=True)
    rfc_ref[1] = row_min

    if p.skip_ahead:
        # ---- phase 1 of the sequence-based select: vectorized suffix-min
        # summaries.  wsx[r, l] = min first-candidate over lanes l.. of row
        # r (7 log-doubling rolls; pltpu.roll is circular, so wrapped lanes
        # are masked to _INF before each min).  rsx[sr] = min row summary
        # over window rows sr.. of the two-slab window (recomputed per tile
        # from the slid + fresh row summaries).  Together they make the
        # per-cut frontier lookup O(1) in place of the PR 4 word walk.
        sfx = fc
        step = 1
        while step < 128:
            y = pltpu.roll(sfx, 128 - step, 1)
            sfx = jnp.minimum(sfx, jnp.where(lane < 128 - step, y, _INF))
            step *= 2
        wsx_ref[1] = sfx
        rwin = jnp.concatenate([rfc_ref[0], row_min], axis=0)
        rowi2 = jax.lax.broadcasted_iota(i32, (2 * R, 1), 0)
        rsx = rwin
        step = 1
        while step < 2 * R:
            y = pltpu.roll(rsx, 2 * R - step, 0)
            rsx = jnp.minimum(rsx, jnp.where(rowi2 < 2 * R - step, y, _INF))
            step *= 2
        rsx_ref[...] = rsx
        # Telemetry for the H_SURV/H_CANDS header lanes (benchmarks cdc /
        # bench.py's cdc_adaptive block): per-slab survivor count = rows
        # with any viable candidate, plus the masked candidate population.
        st_ref[6] = st_ref[6] + jnp.sum((row_min != _INF).astype(i32))
        st_ref[7] = st_ref[7] + jnp.sum(cand[0] + cand[1]
                                        + cand[2] + cand[3])

    # ---- sequential frontier scan over the two-slab candidate summaries
    base_row = (t - 1) * R
    covered = (t + 1) * B
    last = t == T - 1

    def rd_nib(jg):
        sr = jnp.clip(jg // 128 - base_row, 0, 2 * R - 1)
        return cmask_ref[sr // R, sr % R, jnp.clip(jg % 128, 0, 127)]

    def rd_rfc(r):
        sr = jnp.clip(r - base_row, 0, 2 * R - 1)
        return rfc_ref[sr // R, sr % R, 0]

    def first_in_word(jg, lo, hi):
        nib = rd_nib(jg)
        best = jnp.full((), _INF, i32)
        for ph in (3, 2, 1, 0):
            pos = 4 * jg + 1 + ph
            hit = (((nib >> ph) & 1) == 1) & (pos >= lo) & (pos <= hi)
            best = jnp.where(hit, pos, best)
        return best

    def find_seq(lo, hi):
        """Phase 2 of the sequence-based select: first candidate >= ``lo``
        in O(1).  ``lo``'s own word resolves by nibble; later words of the
        row come from the within-row suffix-min at ``lane_lo + 1``; later
        rows from the cross-row suffix-min at ``sr + 1``.  Positions in
        words past ``lo``'s are provably >= 4*j_lo + 5 > lo, so the
        suffix reads never surface a pre-``lo`` candidate; a result past
        ``hi`` means "no candidate in window" and the caller's
        ``cpos <= hi`` clamp forces the cut at ``hi`` — identical
        semantics to the PR 4 walk below."""
        j_lo = (lo - 1) // 4
        row_lo = j_lo // 128
        lane_lo = j_lo % 128
        sr = jnp.clip(row_lo - base_row, 0, 2 * R - 1)
        inf = jnp.full((), _INF, i32)
        a = first_in_word(j_lo, lo, inf)
        b = jnp.where(lane_lo < 127,
                      wsx_ref[sr // R, sr % R,
                              jnp.clip(lane_lo + 1, 0, 127)], inf)
        c = jnp.where(sr < 2 * R - 1,
                      rsx_ref[jnp.clip(sr + 1, 0, 2 * R - 1), 0], inf)
        return jnp.minimum(a, jnp.minimum(b, c))

    def find_walk(lo, hi):
        """First candidate pos1 in [lo, hi] (else _INF) via the summaries:
        whole rows skip on the per-row first-candidate value; only the
        partial row containing ``lo`` word-scans."""
        j_lo, j_hi = (lo - 1) // 4, (hi - 1) // 4
        row_lo = j_lo // 128
        rfc0 = rd_rfc(row_lo)
        scan0 = rfc0 < lo          # candidates before lo share lo's row
        row_end_j = row_lo * 128 + 127

        def wbody(i, st):
            j, best = st
            act = scan0 & (best == _INF) & (j <= jnp.minimum(row_end_j,
                                                             j_hi))
            nb = first_in_word(jnp.clip(j, 0, None), lo, hi)
            return (j + 1, jnp.where(act, nb, best))

        _, best0 = jax.lax.fori_loop(0, 128, wbody,
                                     (j_lo, jnp.full((), _INF, i32)))

        def rbody(i, st):
            r, best, dead = st
            act = (best == _INF) & (dead == 0) & (r <= j_hi // 128)
            v = rd_rfc(r)
            found = act & (v >= lo) & (v <= hi)
            # first cand of this row beyond hi => later rows only larger
            stop = act & (v != _INF) & (v > hi)
            return (r + 1, jnp.where(found, v, best),
                    jnp.where(stop, 1, dead))

        r0 = row_lo + scan0.astype(i32)
        trips = p.max_chunk // 512 + 3
        _, best, _ = jax.lax.fori_loop(
            0, trips, rbody, (r0, best0, jnp.full((), 0, i32)))
        return best

    find = find_seq if p.skip_ahead else find_walk

    def cbody(i, s):
        f, nc, ns, nbg, of, done = s
        lo = f + p.min_chunk
        hi = jnp.minimum(f + p.max_chunk, p.true_n)
        go = (done == 0) & (of == 0) & (f < p.true_n) \
            & ((hi <= covered) | last)
        cpos = find(lo, hi)
        cut = jnp.where(cpos <= hi, cpos, hi)
        ln = cut - f
        small = (ln + 9 + 63) // 64 <= p.b_small
        of2 = jnp.where(go & ((nc >= cap) | jnp.where(small, ns >= Ls,
                                                      nbg >= Lb)), 1, of)
        emit = go & (of2 == 0)

        @pl.when(emit)
        def _():
            table_ref[0, TABLE_HDR + nc] = cut

            @pl.when(small)
            def _s():
                ols_ref[0, ns] = f
                ols_ref[1, ns] = ln

            @pl.when(jnp.logical_not(small))
            def _b():
                olb_ref[0, nbg] = f
                olb_ref[1, nbg] = ln

        e = emit.astype(i32)
        return (jnp.where(emit, cut, f), nc + e,
                ns + e * small.astype(i32), nbg + e * (1 - small.astype(i32)),
                of2, jnp.where(emit & (cut >= p.true_n), 1, done))

    trips = 2 * B // p.min_chunk + 2
    s0 = (st_ref[0], st_ref[1], st_ref[2], st_ref[3], st_ref[4], st_ref[5])
    f, nc, ns, nbg, of, done = jax.lax.fori_loop(0, trips, cbody, s0)
    st_ref[0], st_ref[1], st_ref[2] = f, nc, ns
    st_ref[3], st_ref[4], st_ref[5] = nbg, of, done

    @pl.when(last)
    def _hdr():
        table_ref[0, H_COUNT] = nc
        table_ref[0, H_OVERFLOW] = of
        table_ref[0, H_SMALL] = ns
        table_ref[0, H_BIG] = nbg
        table_ref[0, H_SURV] = st_ref[6]
        table_ref[0, H_CANDS] = st_ref[7]


@functools.cache
def _select_call(p: FusedPlan, interpret: bool):
    R, tw = p.R, TABLE_HDR + p.cap
    return pl.pallas_call(
        functools.partial(_select_kernel, p=p),
        grid=(p.T,),
        in_specs=[pl.BlockSpec((R, 128), lambda t: (t, 0))],
        out_specs=[pl.BlockSpec((R, 128), lambda t: (t, 0)),
                   pl.BlockSpec((1, tw), lambda t: (0, 0)),
                   pl.BlockSpec((2, p.Ls), lambda t: (0, 0)),
                   pl.BlockSpec((2, p.Lb), lambda t: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((p.T * R, 128), jnp.uint32),
                   jax.ShapeDtypeStruct((1, tw), jnp.int32),
                   jax.ShapeDtypeStruct((2, p.Ls), jnp.int32),
                   jax.ShapeDtypeStruct((2, p.Lb), jnp.int32)],
        scratch_shapes=[pltpu.VMEM((2, R, 128), jnp.int32),
                        pltpu.VMEM((2, R, 1), jnp.int32)]
        + ([pltpu.VMEM((2, R, 128), jnp.int32),     # wsx: within-row sfx-min
            pltpu.VMEM((2 * R, 1), jnp.int32)]      # rsx: cross-row sfx-min
           if p.skip_ahead else [])
        + [pltpu.VMEM((16, 128), jnp.uint32),
           pltpu.SMEM((8,), jnp.int32)],
        interpret=interpret,
    )


def fused_block(w2d: jax.Array, p: FusedPlan, interpret: bool):
    """Run the fused kernel on one block's (n_pad/512, 128) LE u32 word
    image.  Returns (words_be u32[n_pad/4/128, 128], table i32[1, 8+cap],
    ol_small i32[2, Ls], ol_big i32[2, Lb]); traceable under jit."""
    return _select_call(p, interpret)(w2d)


# --------------------------------------------------------------------------
# Host-facing single-block helper (tests / benchmarks)
# --------------------------------------------------------------------------

def chunks_fused(data: bytes | np.ndarray, mask: int, min_chunk: int,
                 max_chunk: int, *, mask_bits: int = 13,
                 interpret: bool | None = None,
                 skip_ahead: bool | None = None):
    """(cuts, overflowed) with selection fully on device; same cut contract
    as native.cdc_chunk (asserted bit-identical in tests/test_cdc_pallas.py).
    ``overflowed`` reports that cap was exceeded and cuts are INVALID —
    callers must take the oracle path (the resident pipeline's fallback).
    ``skip_ahead`` pins the scan variant (None = the process-level
    ``cdc_skip_ahead()`` gate) — both variants must produce identical cuts,
    which the A/B tests sweep."""
    a = (np.frombuffer(data, dtype=np.uint8)
         if not isinstance(data, np.ndarray) else data)
    if a.size == 0:
        return np.empty(0, dtype=np.uint64), False
    if interpret is None:
        interpret = cdc_pallas_mode() != "mosaic"
    p = plan_for(a.size, mask, mask_bits, min_chunk, max_chunk,
                 b_small=1 << 30, b_big=1 << 30, skip_ahead=skip_ahead)
    buf = np.zeros(p.n_pad, dtype=np.uint8)
    buf[:a.size] = a
    w2d = jax.device_put(buf.view(np.uint32).reshape(-1, 128))
    _, table, _, _ = fused_block(w2d, p, interpret)
    tb = np.asarray(table)[0]
    nc, of = int(tb[H_COUNT]), int(tb[H_OVERFLOW])
    return tb[TABLE_HDR:TABLE_HDR + nc].astype(np.uint64), bool(of)


# --------------------------------------------------------------------------
# Scan-only kernel: per-shard candidate nibbles for parallel/sharded.py
# --------------------------------------------------------------------------

def _scan_kernel(pos_ref, mask_ref, w_ref, nib_ref, hist_ref, *, R: int,
                 m: int):
    t = pl.program_id(0)
    i32 = jnp.int32

    @pl.when(t == 0)
    def _init():
        hist_ref[...] = jnp.zeros_like(hist_ref)

    w = w_ref[...]
    h = _tile_hashes(w, hist_ref)
    row = jax.lax.broadcasted_iota(i32, (R, 128), 0)
    lane = jax.lax.broadcasted_iota(i32, (R, 128), 1)
    byte0 = (t * (R * 128) + row * 128 + lane) * 4    # ext byte of phase 0
    mask = mask_ref[0, 0]
    base = pos_ref[0, 0]
    nib = jnp.zeros((R, 128), i32)
    for ph in range(4):
        e = byte0 + ph
        pos1 = base + e - (WINDOW - 1)                 # ext prefix = 32 bytes
        c = ((h[ph] & mask) == 0) & (pos1 >= gear.MIN_CANDIDATE_POS1) \
            & (e >= WINDOW) & (e < WINDOW + m)
        nib = nib | (c.astype(i32) << ph)
    nib_ref[...] = nib


@functools.cache
def _scan_call(T: int, R: int, m: int, interpret: bool):
    return pl.pallas_call(
        functools.partial(_scan_kernel, R=R, m=m),
        grid=(T,),
        in_specs=[pl.BlockSpec((1, 1), lambda t: (0, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec((1, 1), lambda t: (0, 0),
                               memory_space=pltpu.SMEM),
                  pl.BlockSpec((R, 128), lambda t: (t, 0))],
        out_specs=pl.BlockSpec((R, 128), lambda t: (t, 0)),
        out_shape=jax.ShapeDtypeStruct((T * R, 128), jnp.int32),
        scratch_shapes=[pltpu.VMEM((16, 128), jnp.uint32)],
        interpret=interpret,
    )


@functools.cache
def _le_weights(b0: int) -> np.ndarray:
    """(256, 64) f32 block-diagonal for LITTLE-endian 16-bit halves:
    output t = byte[4t+b0] + 256*byte[4t+b0+1] (exact in f32; the BE
    variant is ops/resident.py _combine_weights)."""
    w = np.zeros((256, 64), dtype=np.float32)
    for t in range(64):
        w[4 * t + b0, t] = 1.0
        w[4 * t + b0 + 1, t] = 256.0
    return w


def le_word_image(block: jax.Array) -> jax.Array:
    """u8[N] -> native little-endian u32[N/4] words via the same two-matmul
    MXU combine as resident.be_word_image (a u8->u32 bitcast materializes
    the 32x-padded minor-dim-4 layout, PERF_NOTES.md round 2)."""
    bf = block.astype(jnp.float32).reshape(-1, 256)
    lo = jnp.dot(bf, jnp.asarray(_le_weights(0)),
                 preferred_element_type=jnp.float32)
    hi = jnp.dot(bf, jnp.asarray(_le_weights(2)),
                 preferred_element_type=jnp.float32)
    return ((hi.astype(jnp.uint32) << 16)
            | lo.astype(jnp.uint32)).reshape(-1)


def _pack_nibbles(nib: jax.Array) -> jax.Array:
    """Per-word candidate nibbles -> little-endian u32 bitmap words (8
    nibbles per word), the exact bit layout of gear.pack_bitmap_words:
    two exact-f32 matmul halves (< 2^16) + shift-or."""
    f = nib.astype(jnp.float32).reshape(-1, 8)
    wv = jnp.asarray(np.array([1.0, 16.0, 256.0, 4096.0], np.float32))
    lo = jnp.dot(f[:, :4], wv, preferred_element_type=jnp.float32)
    hi = jnp.dot(f[:, 4:], wv, preferred_element_type=jnp.float32)
    return lo.astype(jnp.uint32) | (hi.astype(jnp.uint32) << 16)


def local_candidate_words_pallas(local: jax.Array, mask: jax.Array,
                                 n_seq: int, *, interpret: bool):
    """Pallas form of sharded._local_candidate_words: same ppermute halo,
    same packed-bitmap contract (bit k of word w = pos 32w+k+1), the scan
    itself fused in one kernel.  Runs inside shard_map; ``local`` u8[m],
    m % 256 == 0."""
    m = local.shape[0]
    idx = jax.lax.axis_index("seq")
    halo = jax.lax.ppermute(local[-(WINDOW - 1):], "seq",
                            [(i, i + 1) for i in range(n_seq - 1)])
    # One leading zero byte word-aligns the 31-byte halo; G[0] == 0 so it
    # never perturbs a hash (same zero-identity the halo itself relies on).
    ext = jnp.concatenate([jnp.zeros(1, jnp.uint8), halo, local])
    R = 128
    B = R * 512
    ext = jnp.pad(ext, (0, (-ext.shape[0]) % B))
    w2d = le_word_image(ext).reshape(-1, 128)
    T = w2d.shape[0] // R
    pos_base = (idx * m).astype(jnp.int32).reshape(1, 1)
    m32 = jax.lax.bitcast_convert_type(mask.astype(jnp.uint32),
                                       jnp.uint32).reshape(1, 1)
    nib = _scan_call(T, R, m, interpret)(pos_base, m32, w2d)
    nib_local = nib.reshape(-1)[WINDOW // 4:WINDOW // 4 + m // 4]
    words = _pack_nibbles(nib_local)
    bits = (nib_local & 1) + ((nib_local >> 1) & 1) \
        + ((nib_local >> 2) & 1) + ((nib_local >> 3) & 1)
    return words, jnp.sum(bits)
