"""Device-resident block reduction pipeline.

The naive composition (ops.gear then ops.sha256) moves the block host->device
for the CDC scan, back to the host, and *again* to the device as padded SHA
lane buffers — ~2.2x the block over the wire.  On the PCIe/tunnel path that
transfer dominates end-to-end throughput (PERF_NOTES.md); the reference has
the same structural flaw in CPU terms: the reference re-walks the block
once per stage (chunking DataDeduplicator.java:264-307, then hashing
:536-650, then storing :652-845) from Java heap buffers.

This pipeline crosses the block to HBM **once** and keeps every per-byte pass
on device:

1. ``_prep`` (one dispatch): big-endian u32 word image + all-position Gear
   candidate scan; only the sparse candidate words come back (O(chunks)).
2. Host: min/max cut selection over sparse candidates (native C++), chunk
   bucketing — O(chunks) control work.
3. ``_bucket_sha`` (one dispatch per size bucket): lanes are *gathered on
   device* from the resident word image (vmapped dynamic_slice = Mosaic DMAs),
   byte-aligned with a VPU funnel shift (chunk offsets are arbitrary bytes;
   the gather is word-granular), SHA-padded in word space, and hashed by the
   lane-parallel compression scan (ops.sha256.sha256_words).  Only digests
   come back.

Host<->device traffic per 64 MiB block: 64 MiB H2D + ~100 KiB of offsets
down, ~250 KiB of candidates+digests up.  All readbacks are started with
``copy_to_host_async`` so a caller that overlaps blocks (submit k+1 before
finishing k) hides dispatch and D2H latency entirely.

Fused front end (default on TPU, gated by HDRF_CDC_PALLAS): the batched
path routes stages 1-2 through ops/cdc_pallas.py instead — one Pallas
kernel forms the BE word image AND selects the final cuts on device,
binning chunk offset/length lanes into two fixed-capacity device tables
that feed the bucket SHA **without any host round trip**: the SHA
dispatches are enqueued before the cut table is read back, so the
candidate D2H and one awaited dispatch boundary per group (~100 ms each
through the tunnel) disappear from the steady state.  A kernel-reported
capacity overflow (header count) falls back to this module's XLA prep +
host native-select path, which also remains the oracle and the CPU-mesh /
device-resident-input path.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from hdrf_tpu.config import CdcConfig
from hdrf_tpu.ops import gear
from hdrf_tpu.ops.dispatch import gear_mask
from hdrf_tpu.ops.sha256 import sha256_words
from hdrf_tpu.utils import device_ledger as _ledger


# Block padding grid: lcm of the bitmap pack row (256 bytes) and the
# 128-word (512-byte) row tiling of the Pallas DMA gather's word image.
_PAD_GRID = 512


def _bucket_of(nb: int) -> int:
    """Bucket = next power of two of the padded SHA block count (<=2x waste)."""
    return 1 << int(nb - 1).bit_length()


def _lane_count(n: int) -> int:
    if n <= 128:
        return 128
    return 1 << int(n - 1).bit_length()


def _lane_count_geo(n: int) -> int:
    """Lane count rounded up to steps of 1/16th of the next power of two:
    pad waste <= 12.5% even just above a power of two (vs <= 50% for pow2
    rounding), with a small jit shape space (8 distinct lane counts per
    octave, since an octave spans top/2..top in top/16 steps)."""
    if n <= 128:
        return 128
    top = 1 << int(n - 1).bit_length()
    step = max(top // 16, 128)
    return -(-n // step) * step


_COMBINE_ROW = 256  # input bytes per matmul row -> 64 output words


@functools.cache
def _combine_weights(byte0: int) -> "np.ndarray":
    """(256, 64) f32 block-diagonal: output t = byte[4t+byte0]*256 +
    byte[4t+byte0+1] — one 16-bit big-endian half per word, exact in f32."""
    w = np.zeros((_COMBINE_ROW, _COMBINE_ROW // 4), dtype=np.float32)
    for t in range(_COMBINE_ROW // 4):
        w[4 * t + byte0, t] = 256.0
        w[4 * t + byte0 + 1, t] = 1.0
    return w


def be_word_image(block: jax.Array) -> jax.Array:
    """u8[N] -> big-endian u32[N/4] word image, via MXU block-diagonal
    combines.  Neither astype(u32) on a (N/4, 4) view nor a u8->u32 bitcast
    works at speed here: both make XLA materialize a 32x-padded minor-dim-4
    intermediate (measured 27 ms per 64 MiB — the dominant _prep cost).  Two
    matmuls build the 16-bit halves exactly in f32 (values <= 2^16-1 < 2^24),
    then one integer shift-or fuses them: pure bandwidth + trivial MXU work.
    Shared by the CDC prep pass and the LZ4 match scan (ops/lz4_tpu.py)."""
    bf = block.astype(jnp.float32).reshape(-1, _COMBINE_ROW)
    hi = jnp.dot(bf, jnp.asarray(_combine_weights(0)),
                 preferred_element_type=jnp.float32)
    lo = jnp.dot(bf, jnp.asarray(_combine_weights(2)),
                 preferred_element_type=jnp.float32)
    return ((hi.astype(jnp.uint32) << 16)
            | lo.astype(jnp.uint32)).reshape(-1)


def _prep_impl(block: jax.Array, mask: int, cap: int, pad_words: int):
    """One pass over the resident block: BE word image + candidate scan.

    Returns (words u32[N/4 + pad_words], cand i32[1 + 2*cap]) where cand
    packs [count, word_idx..., word_val...] into a single D2H transfer.
    """
    words = be_word_image(block)
    words = jnp.concatenate([words, jnp.zeros(pad_words, jnp.uint32)])

    cw = gear.candidate_bitmap_words(block, jnp.uint32(mask))
    nz = cw != 0
    (idx,) = jnp.nonzero(nz, size=cap, fill_value=cw.shape[0])
    vals = jnp.take(cw, idx, fill_value=0)
    count = jnp.sum(nz.astype(jnp.int32))
    cand = jnp.concatenate([count[None], idx.astype(jnp.int32),
                            jax.lax.bitcast_convert_type(vals, jnp.int32)])
    return words, cand


_prep = functools.partial(jax.jit, static_argnames=("mask", "cap",
                                                    "pad_words"))(_prep_impl)


@functools.partial(jax.jit, static_argnames=("mask", "cap", "pad_words"))
def _prep_batch(blocks: jax.Array, mask: int, cap: int, pad_words: int):
    """Per-block _prep over K equal-length blocks in ONE device program:
    one dispatch and one candidate readback for the whole group.  The loop
    is UNROLLED (K is a shape, so a jit-cache key): measured 8.5x faster
    than ``lax.map`` (whose per-iteration staging defeats cross-stage
    fusion) and — unlike ``vmap`` — free of the 32x-padded minor-dim-4
    batch layouts that OOM at group scale.  Through a high-latency
    transport (~100 ms per awaited round trip on the dev tunnel) dispatch
    count dominates device time, making stage batching the single biggest
    throughput lever (PERF_NOTES.md)."""
    outs = [_prep_impl(blocks[k], mask, cap, pad_words)
            for k in range(blocks.shape[0])]
    return (jnp.stack([w for w, _ in outs]),
            jnp.stack([c for _, c in outs]))


def sha_pad_messages(words: jax.Array, ol: jax.Array,
                     bucket: int) -> tuple[jax.Array, jax.Array]:
    """Gather + byte-align + SHA-pad one size bucket of chunks into padded
    message words (no hashing).  Shared by :func:`_bucket_sha` and the
    mesh-sharded reduction step (parallel/sharded.py), which hashes the
    same messages per shard under shard_map.

    words: u32[NW] resident BE word image (zero-padded so no slice clamps).
    ol: i32[2, L] — row 0 chunk byte offsets, row 1 chunk byte lengths,
    lens + 9 <= bucket * 64.  Returns (msgs u32[L, bucket*16], nb i64[L]).
    """
    offs, lens = ol[0], ol[1]
    W = bucket * 16  # u32 words per lane
    q = offs // 4
    s8 = ((offs % 4) * 8).astype(jnp.uint32)[:, None]

    lanes = jax.vmap(lambda o: jax.lax.dynamic_slice(words, (o,), (W + 1,)))(q)
    a, b = lanes[:, :W], lanes[:, 1:]
    # Funnel shift: byte-misaligned chunk words from two adjacent aligned words.
    c = jnp.where(s8 == 0, a, (a << s8) | (b >> (jnp.uint32(32) - s8)))

    # SHA padding in word space: keep data words, splice 0x80 at byte ``len``,
    # zero the tail, write the 64-bit big-endian bit length in the last words.
    wl = (lens // 4)[:, None]
    r8 = ((lens % 4) * 8).astype(jnp.uint32)[:, None]
    j = jnp.arange(W, dtype=jnp.int32)[None, :]
    keep = jnp.where(r8 == 0, jnp.uint32(0),
                     jnp.uint32(0xFFFFFFFF) << (jnp.uint32(32) - r8))
    marker = jnp.uint32(0x80) << (jnp.uint32(24) - r8)
    boundary = (c & keep) | marker
    out = jnp.where(j < wl, c, jnp.where(j == wl, boundary, jnp.uint32(0)))
    nb = (lens + 9 + 63) // 64
    last = nb * 16 - 1
    bitlen = (lens.astype(jnp.uint32) * 8)[:, None]
    out = jnp.where(j == last[:, None], bitlen, out)
    return out, nb


@functools.partial(jax.jit, static_argnames=("bucket",))
def _bucket_sha(words: jax.Array, ol: jax.Array, bucket: int) -> jax.Array:
    """Gather + byte-align + SHA-pad + hash one size bucket of chunks.

    words: u32[NW] resident BE word image (zero-padded so no slice clamps).
    ol: i32[2, L] — row 0 chunk byte offsets, row 1 chunk byte lengths
    (one packed upload: each tiny H2D pays a fixed tunnel cost),
    lens + 9 <= bucket * 64.  Returns u8[L, 32].
    """
    out, nb = sha_pad_messages(words, ol, bucket)
    if jax.default_backend() == "cpu":
        return sha256_words(out, nb.astype(jnp.int32))
    from hdrf_tpu.ops.sha256_pallas import sha256_words_pallas

    return sha256_words_pallas(out, nb.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("bucket",))
def _bucket_sha_dma(words: jax.Array, ol: jax.Array, bucket: int):
    """TPU fast path for _bucket_sha: the Pallas DMA gather kernel builds
    the padded messages (~0.3 us/lane vs ~2-5 us/lane for the XLA gather —
    the dominant device cost once dispatches are batched), then the Pallas
    SHA kernel hashes them.  Same contract and bit-identical output."""
    from hdrf_tpu.ops.gather_pallas import gather_pad_messages
    from hdrf_tpu.ops.sha256_pallas import sha256_words_pallas

    msgs = gather_pad_messages(words, ol, bucket)
    nb = (ol[1] + 9 + 63) // 64
    return sha256_words_pallas(msgs, nb.astype(jnp.int32))


def _bucket_sha_best(words: jax.Array, ol, bucket: int):
    """DMA-gather path on TPU when the word image tiles into 128-word rows;
    XLA gather otherwise (CPU backend, odd image sizes)."""
    if jax.default_backend() != "cpu" and words.shape[0] % 128 == 0:
        return _bucket_sha_dma(words, jax.device_put(ol), bucket)
    return _bucket_sha(words, jax.device_put(ol), bucket)


def _bucket_sha_dev(words: jax.Array, ol: jax.Array, bucket: int):
    """_bucket_sha_best for an ALREADY-device-resident ol table (the fused
    CDC path: the offset/length lanes never visit the host, so there is no
    device_put).  Traceable under jit."""
    if jax.default_backend() != "cpu" and words.shape[0] % 128 == 0:
        return _bucket_sha_dma(words, ol, bucket)
    return _bucket_sha(words, ol, bucket)


@functools.partial(jax.jit, static_argnames=("plan", "pad_words", "b_big",
                                             "interpret"))
def _fused_batch(w3d: jax.Array, plan, pad_words: int, b_big: int,
                 interpret: bool):
    """K-block fused CDC + bucket SHA in ONE device program (the loop is
    unrolled per the _prep_batch precedent).  Per block: the cdc_pallas
    select kernel emits the BE word image, the cut table, and two binned
    offset/length lane tables; lane offsets are rebased to the flat
    multi-block word image and the two bucket SHA passes run over the
    concatenated fixed-capacity lanes — chunk COUNTS are not needed to
    enqueue, which is what removes the awaited prep boundary.

    Returns (tables i32[K, 8+cap], digests u8[K*Ls + K*Lb, 32]) with small
    lanes first: digest row of block k's small-lane j = k*Ls + j, big-lane
    j = K*Ls + k*Lb + j.
    """
    from hdrf_tpu.ops import cdc_pallas

    k = w3d.shape[0]
    stride_words = plan.T * plan.R * 128 + pad_words
    words_l, tables_l, ols_l, olb_l = [], [], [], []
    for i in range(k):
        if w3d.dtype == jnp.uint8:
            # HBM-resident u8 block (the streamed worker deployment): LE
            # words via the MXU combine — a u8->u32 bitcast materializes
            # the 32x-padded minor-dim-4 layout (be_word_image's rationale).
            padded = jnp.pad(w3d[i], (0, plan.n_pad - w3d.shape[1]))
            w2d = cdc_pallas.le_word_image(padded).reshape(-1, 128)
        else:
            w2d = w3d[i]
        wbe, table, ols, olb = cdc_pallas.fused_block(w2d, plan,
                                                      interpret)
        words_l.append(jnp.concatenate(
            [wbe.reshape(-1), jnp.zeros(pad_words, jnp.uint32)]))
        tables_l.append(table[0])
        base = jnp.int32(i * stride_words * 4)
        ols_l.append(ols.at[0].add(base))
        olb_l.append(olb.at[0].add(base))
    words = jnp.concatenate(words_l)
    ol_s = jnp.concatenate(ols_l, axis=1)
    ol_b = jnp.concatenate(olb_l, axis=1)
    digs = jnp.concatenate([_bucket_sha_dev(words, ol_s, plan.b_small),
                            _bucket_sha_dev(words, ol_b, b_big)], axis=0)
    return jnp.stack(tables_l), digs


@dataclasses.dataclass
class BatchJob:
    """A group of K equal-length blocks reduced with one dispatch + one
    readback per stage (vs 2 awaited round trips PER BLOCK on the
    per-block path — the dominant cost through a high-latency transport)."""
    k: int                    # blocks in the group
    n: int                    # padded bytes per block (uniform)
    blocks: jax.Array | None  # (K, n) resident u8 (until cuts final)
    words: jax.Array          # (K, n/4 + pad_words) resident BE word image
    cand: jax.Array           # (K, 1 + 2*cap) packed candidates (D2H async)
    cap: int
    true_n: int               # unpadded byte length per block
    cuts: list[np.ndarray] | None = None
    _sha_parts: tuple | None = None
    _ev: object = None        # ledger token: prep dispatch -> cand readback
    _ev_sha: list | None = None  # ledger tokens: sha dispatches -> digest rb
    # Fused-CDC path state (cdc_pallas): cuts selected on device, SHA
    # enqueued against fixed-capacity lane tables before any readback.
    fused: bool = False
    tables: jax.Array | None = None   # (K, 8+cap) cut tables (D2H async)
    plan: object = None               # cdc_pallas.FusedPlan
    _digs: jax.Array | None = None    # (K*Ls + K*Lb, 32) fused digests
    _host: list | None = None         # host u8 blocks for overflow fallback
    # Mixed-size groups (bucket-padded coalescing): per-block unpadded
    # lengths; None means every block is true_n bytes.
    true_ns: list[int] | None = None


def _host_sizes(datas) -> list[int]:
    return [d.size if isinstance(d, np.ndarray) else len(d) for d in datas]


@dataclasses.dataclass
class BlockJob:
    n: int
    block: jax.Array | None   # resident u8 image (until cuts are final)
    words: jax.Array          # resident BE word image
    cand: jax.Array           # packed candidate readback (D2H in flight)
    cap: int
    cuts: np.ndarray | None = None
    _sha_parts: tuple | None = None  # (sels, lane_counts, digests_dev)
    _ev: object = None        # ledger token: prep dispatch -> cand readback
    _ev_sha: list | None = None  # ledger tokens: sha dispatches -> digest rb


class ResidentReducer:
    """Async block-reduction front end over the device-resident pipeline.

    Usage (overlapped):
        jobs = [r.submit(b) for b in blocks]      # H2D + scan dispatches
        for j in jobs: r.start_sha(j)             # cut select + SHA dispatches
        results = [r.finish(j) for j in jobs]     # (cuts, digests)
    """

    def __init__(self, cdc: CdcConfig | None = None,
                 fused_mode: str | None = None,
                 skip_ahead: bool | None = None):
        from hdrf_tpu.ops.cdc_pallas import cdc_pallas_mode, cdc_skip_ahead

        self.cdc = cdc or CdcConfig()
        self.mask = gear_mask(self.cdc)
        # 'mosaic' | 'interpret' | 'off' — resolved once so a reducer's jit
        # cache stays coherent; dispatch.py keys its reducer cache on this.
        self.fused = fused_mode if fused_mode is not None \
            else cdc_pallas_mode()
        # Scan-variant pin (skip-ahead + sequence select vs the PR 4 walk),
        # resolved once for the same jit-cache-coherence reason.
        self._skip_ahead = skip_ahead if skip_ahead is not None \
            else cdc_skip_ahead()
        # Gather windows must never clamp: pad the word image by the widest
        # bucket (max_chunk rounded up) + the funnel-shift lookahead word,
        # rounded to the 128-word row grid the Pallas DMA gather requires.
        max_nb = (self.cdc.max_chunk + 9 + 63) // 64
        self.pad_words = -(-(_bucket_of(max_nb) * 16 + 16) // 128) * 128
        # Two-bucket SHA dispatch plan: small bucket = exactly 2x the average
        # chunk, big bucket = exactly max_chunk.  Bucket widths are jit-cache
        # keys, not layout constraints — pow2 rounding here would double the
        # padded SHA work for the mass of the distribution.
        # Clamped to the big bucket: a degenerate config whose expected
        # chunk (2<<mask_bits) exceeds max_chunk must not widen the small
        # gather window past the word-image padding.
        self._b_small = max(1, min((2 << self.cdc.mask_bits) // 64, max_nb))
        self._b_big = max_nb
        # Batched path: four buckets (avg, 2x, 4x, max) — padded gather
        # bytes drop from ~2.45x to ~1.53x of the block at the measured
        # chunk-size distribution, and with stage batching the extra
        # dispatches are enqueued, not awaited, so they cost device time
        # only.
        self._buckets = sorted({b for b in (self._b_small // 2,
                                            self._b_small,
                                            2 * self._b_small, max_nb)
                                if 0 < b <= max_nb})

    # ----------------------------------------------------- batched pipeline

    def submit_many(self, datas) -> BatchJob:
        """Start reduction of K equal-length blocks as ONE device program.

        ``datas``: list of host byte buffers (bytes / u8 ndarray) all the
        same length, or an already-HBM-resident (K, n) u8 device array
        (the streamed TPU-worker deployment).

        Host-byte groups route through the fused Pallas CDC kernel when
        enabled (cuts selected on device, SHA enqueued with no candidate
        readback); device-resident inputs and ``fused == 'off'`` take the
        XLA prep + host-select path.  Mixed-length host groups (the
        bucket-padded coalescer) always take the XLA path, padded to the
        longest member — the fused kernel's plan is per-length.
        """
        if self.fused != "off":
            if isinstance(datas, jax.Array) or len(
                    set(_host_sizes(datas))) == 1:
                return self._submit_many_fused(datas)
        return self._submit_many_xla(datas)

    def _submit_many_xla(self, datas) -> BatchJob:
        pad_extra = 0
        true_ns = None
        if isinstance(datas, jax.Array):
            k, n = datas.shape
            assert n > 0 and n % _PAD_GRID == 0
            true_n = n
            stacked = datas
        else:
            arrs = [np.frombuffer(d, dtype=np.uint8)
                    if not isinstance(d, np.ndarray) else d for d in datas]
            true_ns = [a.size for a in arrs]
            true_n = max(true_ns)
            assert true_n > 0
            n_pad = true_n + (-true_n) % _PAD_GRID
            if any(a.size != n_pad for a in arrs):
                arrs = [a if a.size == n_pad
                        else np.concatenate(
                            [a, np.zeros(n_pad - a.size, np.uint8)])
                        for a in arrs]
            if min(true_ns) != true_n:
                # A shorter member's zero tail is a DENSE candidate region
                # (the gear hash of zeros is zero, and 0 & mask == 0): one
                # candidate word per 32 pad bytes must fit the packed
                # readback, or every mixed group would pay the prep_retry
                # round trip the capacity formula exists to avoid.
                pad_extra = (n_pad - min(true_ns)) // 32 + 2
            else:
                true_ns = None
            stacked = jax.device_put(np.stack(arrs))
            k, n = stacked.shape
        # int32 flat-byte-offset headroom for the bucket gather
        assert k * (n + 4 * self.pad_words) < (1 << 31), \
            "batch too large for i32 flat offsets; split it"
        cap = max(1, min(n // 32,
                         max(1024, (n >> max(self.cdc.mask_bits - 1, 0))
                             + 1024) + pad_extra))
        ev = _ledger.dispatch(
            "resident.prep_batch", batch=k,
            h2d_bytes=0 if isinstance(datas, jax.Array) else k * n,
            key=(k, n, cap))
        words, cand = _prep_batch(stacked, self.mask, cap, self.pad_words)
        cand.copy_to_host_async()
        return BatchJob(k=k, n=n, blocks=stacked, words=words, cand=cand,
                        cap=cap, true_n=true_n, true_ns=true_ns, _ev=ev)

    def _submit_many_fused(self, datas) -> BatchJob:
        """Fused-kernel group submit: ONE program selects cuts on device
        and hashes both lane buckets; the cut-table readback and the SHA
        digests start D2H together — nothing is awaited here."""
        from hdrf_tpu.ops import cdc_pallas

        if isinstance(datas, jax.Array):
            # HBM-resident group: LE words form on device (MXU combine in
            # _fused_batch); the raw array doubles as the fallback input.
            k, true_n = datas.shape
            assert true_n > 0 and true_n % _PAD_GRID == 0
            arrs, w3d, h2d = datas, datas, 0
        else:
            arrs = [np.ascontiguousarray(
                        np.frombuffer(d, dtype=np.uint8)
                        if not isinstance(d, np.ndarray) else d)
                    for d in datas]
            true_n = arrs[0].size
            assert all(a.size == true_n for a in arrs), \
                "submit_many needs equal lengths"
            assert true_n > 0
            k, w3d = len(arrs), None
        plan = cdc_pallas.plan_for(true_n, self.mask, self.cdc.mask_bits,
                                   self.cdc.min_chunk, self.cdc.max_chunk,
                                   self._b_small, self._b_big,
                                   skip_ahead=self._skip_ahead)
        stride = plan.n_pad + 4 * self.pad_words
        assert k * stride < (1 << 31), \
            "batch too large for i32 flat offsets; split it"
        if w3d is None:
            buf = np.zeros((k, plan.n_pad), dtype=np.uint8)
            for i, a in enumerate(arrs):
                buf[i, :true_n] = a
            # Host-side u32 view = free little-endian word formation; the
            # kernel byteswaps to BE in-register (no separate MXU pass).
            w3d = jax.device_put(buf.view(np.uint32).reshape(k, -1, 128))
            h2d = k * plan.n_pad
        interpret = self.fused == "interpret"
        ev = _ledger.dispatch("resident.cdc_fused", batch=k,
                              h2d_bytes=h2d,
                              key=(k, plan.n_pad, plan.cap, self.fused))
        tables, digs = _fused_batch(w3d, plan, self.pad_words, self._b_big,
                                    interpret)
        tables.copy_to_host_async()
        # SHA is enqueued already — against fixed-capacity lane tables, so
        # no cut count (hence no readback) gates it.  One ledger dispatch
        # per bucket keeps parity with the XLA path's accounting.
        evs = [_ledger.dispatch("resident.sha", batch=k,
                                key=(b, lanes, "fused"))
               for b, lanes in ((plan.b_small, k * plan.Ls),
                                (self._b_big, k * plan.Lb))]
        digs.copy_to_host_async()
        return BatchJob(k=k, n=plan.n_pad, blocks=None, words=None,
                        cand=None, cap=plan.cap, true_n=true_n,
                        fused=True, tables=tables, plan=plan, _digs=digs,
                        _host=arrs, _ev=ev, _ev_sha=evs)

    def _cuts_from_cand(self, cand_row: np.ndarray, cap: int, block,
                        true_n: int) -> np.ndarray:
        """Candidate row -> selected cut points.  The packed layout is
        [count, idx x cap, vals x cap]; a dense-candidate overflow (count >
        cap, e.g. long zero runs where every position hashes to 0) retries
        _prep once with exact capacity, after which 1+count == 1+cap.  The
        ONE place that understands this layout — shared by the per-block
        and batched paths."""
        from hdrf_tpu import native

        count = int(cand_row[0])
        if count > cap:
            cap = count
            ev = _ledger.dispatch("resident.prep_retry",
                                  key=(block.shape, cap))
            _, cd = _prep(block, self.mask, cap, self.pad_words)
            cand_row = np.asarray(cd)
            _ledger.readback(ev, d2h_bytes=cand_row.nbytes)
            count = int(cand_row[0])
        idx = cand_row[1:1 + count].astype(np.uint32)
        vals = cand_row[1 + cap:1 + cap + count].view(np.uint32)
        pos = gear._words_to_positions(idx, vals, true_n)
        return native.cdc_select(pos, true_n, self.cdc.min_chunk,
                                 self.cdc.max_chunk)

    def _start_sha_fused(self, bj: BatchJob) -> None:
        """Await the cut tables (the SHA work is already enqueued), derive
        each chunk's digest row from the kernel's two-bucket binning rule,
        or — on a kernel-reported capacity overflow — discard the fused
        lanes and rerun the whole group through the XLA oracle path (cut
        boundaries are never truncated)."""
        from hdrf_tpu.ops import cdc_pallas as cp

        tables = np.asarray(bj.tables)        # the one awaited readback
        _ledger.readback(bj._ev, d2h_bytes=tables.nbytes)
        bj._ev = None
        bj.tables = None
        if self._skip_ahead:
            # Sequence-select telemetry rides the header lanes of the one
            # readback that already happens — zero extra D2H.
            from hdrf_tpu.reduction import accounting

            accounting.record_scan_summary(
                int(tables[:, cp.H_SURV].sum()),
                int(tables[:, cp.H_CANDS].sum()))
        if tables[:, cp.H_OVERFLOW].any():
            for ev in bj._ev_sha or ():       # fused SHA results discarded
                _ledger.readback(ev, d2h_bytes=0)
            bj._ev_sha = None
            bj._digs = None
            nj = self._submit_many_xla(bj._host)
            bj.fused = False
            bj._host = None
            bj.n, bj.true_n, bj.cap = nj.n, nj.true_n, nj.cap
            bj.true_ns = nj.true_ns
            bj.blocks, bj.words, bj.cand = nj.blocks, nj.words, nj.cand
            bj._ev = nj._ev
            self.start_sha_many(bj)
            return
        plan = bj.plan
        cuts_all, place = [], []
        for i in range(bj.k):
            nc = int(tables[i, cp.H_COUNT])
            cuts = tables[i, cp.TABLE_HDR:cp.TABLE_HDR + nc].astype(
                np.uint64)
            cuts_all.append(cuts)
            starts = np.concatenate([[0], cuts[:-1]]).astype(np.int64)
            lens = cuts.astype(np.int64) - starts
            small = (lens + 9 + 63) // 64 <= plan.b_small
            rank = np.where(small, np.cumsum(small) - 1,
                            np.cumsum(~small) - 1)
            place.append(np.where(small, i * plan.Ls + rank,
                                  bj.k * plan.Ls + i * plan.Lb + rank))
        bj.cuts = cuts_all
        bj._sha_parts = ("fused", place, bj._digs)
        bj._digs = None
        bj._host = None

    def start_sha_many(self, bj: BatchJob) -> None:
        if bj.fused:
            self._start_sha_fused(bj)
            return
        cand = np.asarray(bj.cand)            # ONE readback for the group
        _ledger.readback(bj._ev, d2h_bytes=cand.nbytes)
        bj._ev = None
        cuts_all, starts_all, lens_all = [], [], []
        for k in range(bj.k):
            tn = bj.true_ns[k] if bj.true_ns is not None else bj.true_n
            cuts = self._cuts_from_cand(cand[k], bj.cap, bj.blocks[k], tn)
            starts = np.concatenate([[0], cuts[:-1]]).astype(np.int64)
            cuts_all.append(cuts)
            starts_all.append(starts)
            lens_all.append((cuts - starts).astype(np.int64))
        bj.cuts = cuts_all
        # Global flat lane lists, bucketed by padded SHA block count.
        stride_b = bj.words.shape[1] * 4      # bytes per block row incl. pad
        blk = np.concatenate([np.full(len(c), k, np.int64)
                              for k, c in enumerate(cuts_all)])
        chunk_i = np.concatenate([np.arange(len(c)) for c in cuts_all])
        starts = np.concatenate(starts_all)
        lens = np.concatenate(lens_all)
        nb = (lens + 9 + 63) // 64
        flat_off = blk * stride_b + starts
        parts, sels, evs = [], [], []
        lo = 0
        for B in self._buckets:
            m = (nb > lo) & (nb <= B)
            lo = B
            if not m.any():
                continue
            sel = np.nonzero(m)[0]
            L = _lane_count_geo(sel.size)
            ol = np.zeros((2, L), dtype=np.int32)
            ol[0, :sel.size] = flat_off[sel]
            ol[1, :sel.size] = lens[sel]
            evs.append(_ledger.dispatch("resident.sha", batch=sel.size,
                                        h2d_bytes=ol.nbytes, key=(B, L)))
            parts.append(_bucket_sha_best(bj.words.reshape(-1), ol, B))
            sels.append((blk[sel], chunk_i[sel]))
        if parts:
            alld = (jnp.concatenate(parts, axis=0) if len(parts) > 1
                    else parts[0])
            alld.copy_to_host_async()          # ONE digest readback
        else:
            alld = None
        bj._sha_parts = (sels, [p.shape[0] for p in parts], alld)
        bj._ev_sha = evs
        bj.blocks = None

    def finish_many(self, bj: BatchJob) -> list[tuple[np.ndarray, np.ndarray]]:
        if bj._sha_parts is None:
            self.start_sha_many(bj)
        if bj.fused:
            _, place, digs_dev = bj._sha_parts
            digs = np.asarray(digs_dev)
            for i, ev in enumerate(bj._ev_sha or ()):
                _ledger.readback(ev, d2h_bytes=digs.nbytes if i == 0 else 0)
            bj._ev_sha = None
            bj._sha_parts = None
            return [(c, digs[rows]) for c, rows in zip(bj.cuts, place)]
        sels, lane_counts, digs_dev = bj._sha_parts
        outs = [np.empty((len(c), 32), dtype=np.uint8) for c in bj.cuts]
        if digs_dev is not None:
            digs = np.asarray(digs_dev)
            for i, ev in enumerate(bj._ev_sha or ()):
                _ledger.readback(ev, d2h_bytes=digs.nbytes if i == 0 else 0)
            bj._ev_sha = None
            at = 0
            for (blks, idxs), L in zip(sels, lane_counts):
                rows = digs[at:at + blks.size]
                at += L
                for k in np.unique(blks):
                    m = blks == k
                    outs[int(k)][idxs[m]] = rows[m]
        bj.words = None
        return list(zip(bj.cuts, outs))

    def max_group(self, n: int) -> int:
        """Largest equal-length group of n-byte blocks one submit_many can
        take: bounded by i32 flat byte offsets in the bucket gather and a
        cap on the unrolled _prep_batch program size.  The fused path pads
        to its (larger) supertile grid, so both strides bound the group."""
        n_pad = n + (-n) % _PAD_GRID
        stride = n_pad + 4 * self.pad_words
        if self.fused != "off":
            from hdrf_tpu.ops import cdc_pallas

            plan = cdc_pallas.plan_for(max(n, 1), self.mask,
                                       self.cdc.mask_bits,
                                       self.cdc.min_chunk,
                                       self.cdc.max_chunk,
                                       self._b_small, self._b_big,
                                       skip_ahead=self._skip_ahead)
            stride = max(stride, plan.n_pad + 4 * self.pad_words)
        return max(1, min(((1 << 31) - 1) // stride, 16))

    def reduce_many(self, datas: list) -> list[tuple[np.ndarray, np.ndarray]]:
        """Batched multi-block reduction: groups of equal-length blocks run
        as single device programs (split to fit the i32 offset bound); odd
        sizes fall back to the per-block path.  Results keep input order."""
        arrs = [np.frombuffer(d, dtype=np.uint8)
                if not isinstance(d, np.ndarray) else d for d in datas]
        by_len: dict[int, list[int]] = {}
        for i, a in enumerate(arrs):
            by_len.setdefault(a.size, []).append(i)
        out: list = [None] * len(arrs)
        for size, idxs in by_len.items():
            if size == 0 or len(idxs) == 1:
                for i in idxs:
                    out[i] = self.reduce(arrs[i])
                continue
            g = self.max_group(size)
            for at in range(0, len(idxs), g):
                part = idxs[at:at + g]
                if len(part) == 1:
                    out[part[0]] = self.reduce(arrs[part[0]])
                    continue
                bj = self.submit_many([arrs[i] for i in part])
                self.start_sha_many(bj)
                for i, res in zip(part, self.finish_many(bj)):
                    out[i] = res
        return out

    def submit(self, data: bytes | np.ndarray | jax.Array,
               n: int | None = None) -> BlockJob:
        """Start reduction of one block.  ``data`` may be host bytes or an
        already-HBM-resident u8 device array (the gRPC-streamed TPU-worker
        deployment lands packets in HBM before reduction starts; ``n`` gives
        the true length when the device array carries pad)."""
        if isinstance(data, jax.Array):
            block, n = data, n if n is not None else data.shape[0]
            if block.shape[0] % _PAD_GRID:
                block = jnp.pad(
                    block,
                    (0, _PAD_GRID - block.shape[0] % _PAD_GRID))
        else:
            a = (np.frombuffer(data, dtype=np.uint8)
                 if not isinstance(data, np.ndarray) else data)
            n = a.size
            if n % _PAD_GRID:  # pad to the pack/DMA-row grid; candidates
                # in the zero tail are filtered by _words_to_positions
                a = np.concatenate(
                    [a, np.zeros(_PAD_GRID - n % _PAD_GRID, np.uint8)])
            block = jax.device_put(a)
        if n == 0:
            job = BlockJob(n=0, block=None, words=None, cand=None, cap=0,
                           cuts=np.empty(0, dtype=np.uint64))
            job._sha_parts = ([], [], None)
            return job
        cap = max(1, min(block.shape[0] // 32,
                         max(1024, (n >> max(self.cdc.mask_bits - 1, 0)) + 1024)))
        ev = _ledger.dispatch(
            "resident.prep",
            h2d_bytes=0 if isinstance(data, jax.Array) else block.shape[0],
            key=(block.shape, cap))
        words, cand = _prep(block, self.mask, cap, self.pad_words)
        cand.copy_to_host_async()
        return BlockJob(n=n, block=block, words=words, cand=cand, cap=cap,
                        _ev=ev)

    def start_sha(self, job: BlockJob) -> None:
        if job.cand is None:  # empty block prepared entirely in submit()
            return
        cand = np.asarray(job.cand)
        _ledger.readback(job._ev, d2h_bytes=cand.nbytes)
        job._ev = None
        cuts = self._cuts_from_cand(cand, job.cap, job.block, job.n)
        job.cuts = cuts
        starts = np.concatenate([[0], cuts[:-1]]).astype(np.int64)
        lens = (cuts - starts).astype(np.int64)
        nb = (lens + 9 + 63) // 64
        # TWO fixed buckets, not one per power of two: every dispatch through
        # the tunneled transport costs ~100 ms regardless of payload, so
        # dispatch count dominates; the small bucket covers the mass of the
        # chunk-size distribution (~2x the mean), the big one the tail, and
        # padded-lane waste stays comparable to pow2 bucketing.
        order = np.arange(len(cuts))
        sels, parts, evs = [], [], []
        for sel, B in ((order[nb <= self._b_small], self._b_small),
                       (order[nb > self._b_small], self._b_big)):
            if not sel.size:
                continue
            L = _lane_count(sel.size)
            ol = np.zeros((2, L), dtype=np.int32)
            ol[0, :sel.size] = starts[sel]
            ol[1, :sel.size] = lens[sel]
            evs.append(_ledger.dispatch("resident.sha", batch=sel.size,
                                        h2d_bytes=ol.nbytes, key=(B, L)))
            parts.append(_bucket_sha_best(job.words, ol, B))
            sels.append(sel)
        # One device-side concat -> ONE digest readback (each extra D2H costs
        # a fixed ~100 ms round trip on the tunneled transport).
        if parts:
            alld = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
            alld.copy_to_host_async()
        else:  # empty block: no chunks, no digests
            alld = None
        job._sha_parts = (sels, [p.shape[0] for p in parts], alld)
        job._ev_sha = evs
        job.block = None  # cuts are final; release the u8 image

    def finish(self, job: BlockJob) -> tuple[np.ndarray, np.ndarray]:
        if job._sha_parts is None:
            self.start_sha(job)
        sels, lane_counts, digs_dev = job._sha_parts
        out = np.empty((len(job.cuts), 32), dtype=np.uint8)
        if digs_dev is not None:
            digs = np.asarray(digs_dev)
            for i, ev in enumerate(job._ev_sha or ()):
                _ledger.readback(ev, d2h_bytes=digs.nbytes if i == 0 else 0)
            job._ev_sha = None
            at = 0
            for sel, L in zip(sels, lane_counts):
                out[sel] = digs[at:at + sel.size]
                at += L
        job.words = None  # release the HBM word image
        return job.cuts, out

    def reduce(self, data: bytes | np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Synchronous single-block convenience: (cuts, digests).  Host
        bytes ride the fused group path as a group of one; device-resident
        arrays and n == 0 keep the per-block XLA path."""
        if self.fused != "off" and not isinstance(data, jax.Array):
            a = (np.frombuffer(data, dtype=np.uint8)
                 if not isinstance(data, np.ndarray) else data)
            if a.size:
                bj = self.submit_many([a])
                self.start_sha_many(bj)
                return self.finish_many(bj)[0]
        job = self.submit(data)
        self.start_sha(job)
        return self.finish(job)
