"""Device-resident block reduction pipeline.

The naive composition (ops.gear then ops.sha256) moves the block host->device
for the CDC scan, back to the host, and *again* to the device as padded SHA
lane buffers — ~2.2x the block over the wire.  On the PCIe/tunnel path that
transfer dominates end-to-end throughput (PERF_NOTES.md); the reference has
the same structural flaw in CPU terms: DataDeduplicator.java re-walks the
block once per stage (chunking :264-307, then hashing :536-650, then storing
:652-845) from Java heap buffers.

This pipeline crosses the block to HBM **once** and keeps every per-byte pass
on device:

1. ``_prep`` (one dispatch): big-endian u32 word image + all-position Gear
   candidate scan; only the sparse candidate words come back (O(chunks)).
2. Host: min/max cut selection over sparse candidates (native C++), chunk
   bucketing — O(chunks) control work.
3. ``_bucket_sha`` (one dispatch per size bucket): lanes are *gathered on
   device* from the resident word image (vmapped dynamic_slice = Mosaic DMAs),
   byte-aligned with a VPU funnel shift (chunk offsets are arbitrary bytes;
   the gather is word-granular), SHA-padded in word space, and hashed by the
   lane-parallel compression scan (ops.sha256.sha256_words).  Only digests
   come back.

Host<->device traffic per 64 MiB block: 64 MiB H2D + ~100 KiB of offsets
down, ~250 KiB of candidates+digests up.  All readbacks are started with
``copy_to_host_async`` so a caller that overlaps blocks (submit k+1 before
finishing k) hides dispatch and D2H latency entirely.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from hdrf_tpu.config import CdcConfig
from hdrf_tpu.ops import gear
from hdrf_tpu.ops.dispatch import gear_mask
from hdrf_tpu.ops.sha256 import sha256_words


def _bucket_of(nb: int) -> int:
    """Bucket = next power of two of the padded SHA block count (<=2x waste)."""
    return 1 << int(nb - 1).bit_length()


def _lane_count(n: int) -> int:
    if n <= 128:
        return 128
    return 1 << int(n - 1).bit_length()


@functools.partial(jax.jit, static_argnames=("mask", "cap", "pad_words"))
def _prep(block: jax.Array, mask: int, cap: int, pad_words: int):
    """One pass over the resident block: BE word image + candidate scan.

    Returns (words u32[N/4 + pad_words], cand i32[1 + 2*cap]) where cand
    packs [count, word_idx..., word_val...] into a single D2H transfer.
    """
    b4 = block.reshape(-1, 4).astype(jnp.uint32)
    words = (b4[:, 0] << 24) | (b4[:, 1] << 16) | (b4[:, 2] << 8) | b4[:, 3]
    words = jnp.concatenate([words, jnp.zeros(pad_words, jnp.uint32)])

    cw = gear.candidate_bitmap_words(block, jnp.uint32(mask))
    nz = cw != 0
    (idx,) = jnp.nonzero(nz, size=cap, fill_value=cw.shape[0])
    vals = jnp.take(cw, idx, fill_value=0)
    count = jnp.sum(nz.astype(jnp.int32))
    cand = jnp.concatenate([count[None], idx.astype(jnp.int32),
                            jax.lax.bitcast_convert_type(vals, jnp.int32)])
    return words, cand


@functools.partial(jax.jit, static_argnames=("bucket",))
def _bucket_sha(words: jax.Array, ol: jax.Array, bucket: int) -> jax.Array:
    """Gather + byte-align + SHA-pad + hash one size bucket of chunks.

    words: u32[NW] resident BE word image (zero-padded so no slice clamps).
    ol: i32[2, L] — row 0 chunk byte offsets, row 1 chunk byte lengths
    (one packed upload: each tiny H2D pays a fixed tunnel cost),
    lens + 9 <= bucket * 64.  Returns u8[L, 32].
    """
    offs, lens = ol[0], ol[1]
    W = bucket * 16  # u32 words per lane
    q = offs // 4
    s8 = ((offs % 4) * 8).astype(jnp.uint32)[:, None]

    lanes = jax.vmap(lambda o: jax.lax.dynamic_slice(words, (o,), (W + 1,)))(q)
    a, b = lanes[:, :W], lanes[:, 1:]
    # Funnel shift: byte-misaligned chunk words from two adjacent aligned words.
    c = jnp.where(s8 == 0, a, (a << s8) | (b >> (jnp.uint32(32) - s8)))

    # SHA padding in word space: keep data words, splice 0x80 at byte ``len``,
    # zero the tail, write the 64-bit big-endian bit length in the last words.
    wl = (lens // 4)[:, None]
    r8 = ((lens % 4) * 8).astype(jnp.uint32)[:, None]
    j = jnp.arange(W, dtype=jnp.int32)[None, :]
    keep = jnp.where(r8 == 0, jnp.uint32(0),
                     jnp.uint32(0xFFFFFFFF) << (jnp.uint32(32) - r8))
    marker = jnp.uint32(0x80) << (jnp.uint32(24) - r8)
    boundary = (c & keep) | marker
    out = jnp.where(j < wl, c, jnp.where(j == wl, boundary, jnp.uint32(0)))
    nb = (lens + 9 + 63) // 64
    last = nb * 16 - 1
    bitlen = (lens.astype(jnp.uint32) * 8)[:, None]
    out = jnp.where(j == last[:, None], bitlen, out)
    if jax.default_backend() == "cpu":
        return sha256_words(out, nb.astype(jnp.int32))
    from hdrf_tpu.ops.sha256_pallas import sha256_words_pallas

    return sha256_words_pallas(out, nb.astype(jnp.int32))


@dataclasses.dataclass
class BlockJob:
    n: int
    block: jax.Array | None   # resident u8 image (until cuts are final)
    words: jax.Array          # resident BE word image
    cand: jax.Array           # packed candidate readback (D2H in flight)
    cap: int
    cuts: np.ndarray | None = None
    _sha_parts: tuple | None = None  # (sels, lane_counts, digests_dev)


class ResidentReducer:
    """Async block-reduction front end over the device-resident pipeline.

    Usage (overlapped):
        jobs = [r.submit(b) for b in blocks]      # H2D + scan dispatches
        for j in jobs: r.start_sha(j)             # cut select + SHA dispatches
        results = [r.finish(j) for j in jobs]     # (cuts, digests)
    """

    def __init__(self, cdc: CdcConfig | None = None):
        self.cdc = cdc or CdcConfig()
        self.mask = gear_mask(self.cdc)
        # Gather windows must never clamp: pad the word image by the widest
        # bucket (max_chunk rounded up) + the funnel-shift lookahead word.
        max_nb = (self.cdc.max_chunk + 9 + 63) // 64
        self.pad_words = _bucket_of(max_nb) * 16 + 16
        # Two-bucket SHA dispatch plan: small bucket = exactly 2x the average
        # chunk, big bucket = exactly max_chunk.  Bucket widths are jit-cache
        # keys, not layout constraints — pow2 rounding here would double the
        # padded SHA work for the mass of the distribution.
        self._b_small = (2 << self.cdc.mask_bits) // 64
        self._b_big = max_nb

    def submit(self, data: bytes | np.ndarray | jax.Array,
               n: int | None = None) -> BlockJob:
        """Start reduction of one block.  ``data`` may be host bytes or an
        already-HBM-resident u8 device array (the gRPC-streamed TPU-worker
        deployment lands packets in HBM before reduction starts; ``n`` gives
        the true length when the device array carries pad)."""
        if isinstance(data, jax.Array):
            block, n = data, n if n is not None else data.shape[0]
            if block.shape[0] % gear._PACK_ROW:
                block = jnp.pad(
                    block,
                    (0, gear._PACK_ROW - block.shape[0] % gear._PACK_ROW))
        else:
            a = (np.frombuffer(data, dtype=np.uint8)
                 if not isinstance(data, np.ndarray) else data)
            n = a.size
            if n % gear._PACK_ROW:  # pad to the bitmap pack grid; candidates
                # in the zero tail are filtered by _words_to_positions
                a = np.concatenate(
                    [a, np.zeros(gear._PACK_ROW - n % gear._PACK_ROW,
                                 np.uint8)])
            block = jax.device_put(a)
        if n == 0:
            job = BlockJob(n=0, block=None, words=None, cand=None, cap=0,
                           cuts=np.empty(0, dtype=np.uint64))
            job._sha_parts = ([], [], None)
            return job
        cap = max(1, min(block.shape[0] // 32,
                         max(1024, (n >> max(self.cdc.mask_bits - 1, 0)) + 1024)))
        words, cand = _prep(block, self.mask, cap, self.pad_words)
        cand.copy_to_host_async()
        return BlockJob(n=n, block=block, words=words, cand=cand, cap=cap)

    def start_sha(self, job: BlockJob) -> None:
        if job.cand is None:  # empty block prepared entirely in submit()
            return
        cand = np.asarray(job.cand)
        count, cap = int(cand[0]), job.cap
        if count > cap:
            # Dense candidates (long zero/constant runs hash to 0, making
            # every position a candidate): one retry with exact capacity.
            cap = count
            _, cand_dev = _prep(job.block, self.mask, cap, self.pad_words)
            cand = np.asarray(cand_dev)
            count = int(cand[0])
        idx = cand[1:1 + count].astype(np.uint32)
        vals = cand[1 + cap:1 + cap + count].view(np.uint32)
        pos = gear._words_to_positions(idx, vals, job.n)
        from hdrf_tpu import native

        cuts = native.cdc_select(pos, job.n, self.cdc.min_chunk,
                                 self.cdc.max_chunk)
        job.cuts = cuts
        starts = np.concatenate([[0], cuts[:-1]]).astype(np.int64)
        lens = (cuts - starts).astype(np.int64)
        nb = (lens + 9 + 63) // 64
        # TWO fixed buckets, not one per power of two: every dispatch through
        # the tunneled transport costs ~100 ms regardless of payload, so
        # dispatch count dominates; the small bucket covers the mass of the
        # chunk-size distribution (~2x the mean), the big one the tail, and
        # padded-lane waste stays comparable to pow2 bucketing.
        order = np.arange(len(cuts))
        sels, parts = [], []
        for sel, B in ((order[nb <= self._b_small], self._b_small),
                       (order[nb > self._b_small], self._b_big)):
            if not sel.size:
                continue
            L = _lane_count(sel.size)
            ol = np.zeros((2, L), dtype=np.int32)
            ol[0, :sel.size] = starts[sel]
            ol[1, :sel.size] = lens[sel]
            parts.append(_bucket_sha(job.words, jax.device_put(ol), B))
            sels.append(sel)
        # One device-side concat -> ONE digest readback (each extra D2H costs
        # a fixed ~100 ms round trip on the tunneled transport).
        if parts:
            alld = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
            alld.copy_to_host_async()
        else:  # empty block: no chunks, no digests
            alld = None
        job._sha_parts = (sels, [p.shape[0] for p in parts], alld)
        job.block = None  # cuts are final; release the u8 image

    def finish(self, job: BlockJob) -> tuple[np.ndarray, np.ndarray]:
        if job._sha_parts is None:
            self.start_sha(job)
        sels, lane_counts, digs_dev = job._sha_parts
        out = np.empty((len(job.cuts), 32), dtype=np.uint8)
        if digs_dev is not None:
            digs = np.asarray(digs_dev)
            at = 0
            for sel, L in zip(sels, lane_counts):
                out[sel] = digs[at:at + sel.size]
                at += L
        job.words = None  # release the HBM word image
        return job.cuts, out

    def reduce(self, data: bytes | np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Synchronous single-block convenience: (cuts, digests)."""
        job = self.submit(data)
        self.start_sha(job)
        return self.finish(job)
