"""Fused Pallas SHA-256 compression kernel.

The XLA lane-parallel scan (ops/sha256.py:93 sha256_words) materializes the message
schedule per block step and round-trips carry state through HBM between scan
iterations; measured ~0.8 GB/s on v5e.  This kernel keeps the compression in
VMEM/registers: the grid walks (lane tiles) x (block chunks), the digest
state lives in the revisited output block across the chunk axis, and the
schedule + 64 rounds are fully unrolled on (8, 128) u32 tiles — the shape
the VPU natively retires.

Same contract as sha256_words: words u32[L, B*16] pre-padded big-endian
messages, nblocks i32[L], digests u8[L, 32].  Bit-identical outputs
(asserted in tests against the XLA path / hashlib).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from hdrf_tpu.ops.sha256 import _H0, _K

_TILE = 8    # lane rows per grid step (sublane dim of the u32 VPU tile)
_BC = 32     # 64-byte blocks per grid step (VMEM stage = _BC*16*_TILE*128*4)


def _rotr(x, n: int):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _kernel(wt_ref, nb_ref, out_ref):
    """Grid (T, B/_BC).  wt (_BC, 16, _TILE, 128) message words; nb
    (_TILE, 128) per-lane block counts; out (8, _TILE, 128) digest state,
    revisited across the chunk axis (same out block for every k)."""
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _():
        for i in range(8):
            out_ref[i] = jnp.full((_TILE, 128), np.uint32(_H0[i]), jnp.uint32)

    state = tuple(out_ref[i] for i in range(8))
    nb = nb_ref[...]
    base = k * _BC

    def block_step(j, state):
        w = [wt_ref[j, i] for i in range(16)]
        for i in range(16, 64):
            s0 = _rotr(w[i - 15], 7) ^ _rotr(w[i - 15], 18) \
                ^ (w[i - 15] >> np.uint32(3))
            s1 = _rotr(w[i - 2], 17) ^ _rotr(w[i - 2], 19) \
                ^ (w[i - 2] >> np.uint32(10))
            w.append(w[i - 16] + s0 + w[i - 7] + s1)
        a, b, c, d, e, f, g, h = state
        for i in range(64):
            s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = (e & f) ^ (~e & g)
            t1 = h + s1 + ch + np.uint32(_K[i]) + w[i]
            s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            maj = (a & b) ^ (a & c) ^ (b & c)
            h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + s0 + maj
        new = tuple(s + v for s, v in zip(state, (a, b, c, d, e, f, g, h)))
        active = (base + j) < nb
        return tuple(jnp.where(active, n, s) for n, s in zip(new, state))

    state = jax.lax.fori_loop(0, _BC, block_step, state)
    for i in range(8):
        out_ref[i] = state[i]


@jax.jit
def sha256_words_pallas(words: jax.Array, nblocks: jax.Array) -> jax.Array:
    """Drop-in replacement for ops.sha256.sha256_words on TPU."""
    L, nwords = words.shape
    B = nwords // 16
    R = L // 128
    # Lane-rows pad UP to a whole number of tiles: flooring T here left the
    # tail rows of non-multiple-of-_TILE lane counts UNPROCESSED — the
    # output block then carried stale device memory, which even masqueraded
    # as correct digests whenever a previous dispatch had hashed the same
    # content into that buffer.
    R_p = max(-(-R // _TILE) * _TILE, _TILE)
    T = R_p // _TILE
    wt = jnp.transpose(words.reshape(L, B, 16), (1, 2, 0)).reshape(
        B, 16, R, 128)
    if B % _BC:
        wt = jnp.pad(wt, ((0, _BC - B % _BC), (0, 0), (0, 0), (0, 0)))
    if R_p != R:
        wt = jnp.pad(wt, ((0, 0), (0, 0), (0, R_p - R), (0, 0)))
        nb2 = jnp.pad(nblocks.reshape(R, 128), ((0, R_p - R), (0, 0)))
    else:
        nb2 = nblocks.reshape(R, 128)
    Bp = wt.shape[0]
    out = pl.pallas_call(
        _kernel,
        grid=(T, Bp // _BC),
        in_specs=[
            pl.BlockSpec((_BC, 16, _TILE, 128), lambda t, k: (k, 0, t, 0)),
            pl.BlockSpec((_TILE, 128), lambda t, k: (t, 0)),
        ],
        out_specs=pl.BlockSpec((8, _TILE, 128), lambda t, k: (0, t, 0)),
        out_shape=jax.ShapeDtypeStruct((8, R_p, 128), jnp.uint32),
    )(wt, nb2.astype(jnp.int32))
    st = out[:, :R].reshape(8, L).T  # (L, 8)
    o = jnp.stack([(st >> np.uint32(s)).astype(jnp.uint8)
                   for s in (24, 16, 8, 0)], axis=-1)
    return o.reshape(L, 32)
