"""Device-side block reconstruction: the read path's TPU half.

SURVEY §2.1 maps the reference's read engine (DataConstructor.java:360-567:
pipelined Redis metadata, group-by-container, decompress, HOT scatter loop
``bBuffer -> data[chunk.bbStart]`` :527-531) to "Pallas gather/decompress".
This module is that re-expression, honest about the split:

- **Container images stay HBM-resident.**  A container is decompressed
  ONCE (host — LZ4's byte-serial output dependence does not map to SPMD;
  the reference decompresses serially too, :482-525) and the uncompressed
  image is cached on device.  Every later reconstruction touching that
  container gathers straight from HBM — no disk, no re-decompress, the
  FsDatasetCache-meets-HBM read path of the co-located deployment.
- **The chunk gather runs on device.**  Chunks become lanes gathered from
  the resident word image with the same funnel-shift byte alignment the
  write path's SHA gather uses (ops/resident._bucket_sha), minus the SHA
  pad splice; one D2H returns the packed lanes and the host lays them into
  the logical block (chunks are contiguous in the output — the "scatter"
  is a single ordered copy pass).

Works on any JAX backend (the CPU mesh tests it); on TPU the XLA gather is
the known-cost path (~2-5 us/lane) with the Pallas DMA variant as the
follow-up lever (PERF_NOTES.md).
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from hdrf_tpu.utils import metrics

_M = metrics.registry("device_recon")

_PAD = 512  # image pad grid (word-image rows)


def _bucket_of(nw: int) -> int:
    return max(1 << int(max(nw, 1) - 1).bit_length(), 16)


@functools.partial(jax.jit, static_argnames=("bucket",))
def gather_lanes_raw(words: jax.Array, ol: jax.Array,
                     bucket: int) -> jax.Array:
    """Raw chunk-lane gather: u32 word image + i32[2, L] (byte offsets,
    byte lengths) -> u32[L, bucket*16] big-endian lane words, byte-aligned
    via funnel shift.  No SHA padding — lanes carry the chunk bytes
    verbatim (tail beyond ``len`` is unspecified; callers slice by len)."""
    offs = ol[0]
    W = bucket * 16
    q = offs // 4
    s8 = ((offs % 4) * 8).astype(jnp.uint32)[:, None]
    lanes = jax.vmap(lambda o: jax.lax.dynamic_slice(words, (o,),
                                                     (W + 1,)))(q)
    a, b = lanes[:, :W], lanes[:, 1:]
    return jnp.where(s8 == 0, a, (a << s8) | (b >> (jnp.uint32(32) - s8)))


class DeviceReconstructor:
    """HBM-resident container image cache + device chunk gather."""

    def __init__(self, budget: int = 256 << 20,
                 headroom: int = (1 << 20) + 4096):
        """``headroom``: zero pad past each image's end so a lane gather
        window (up to the largest chunk, rounded to its pow2 bucket) never
        clamps — a clamped dynamic_slice would silently read earlier
        container bytes.  Must exceed 2x the largest chunk in use."""
        self._budget = budget
        self._headroom = headroom
        self._lock = threading.Lock()
        self._images: dict[int, jax.Array] = {}  # cid -> resident u32 words
        self._sizes: dict[int, int] = {}
        self._used = 0

    def _image(self, cid: int, payload_loader) -> jax.Array:
        with self._lock:
            img = self._images.get(cid)
            if img is not None:
                _M.incr("image_hits")
                return img
        data = payload_loader()  # host decompress happens at most once
        a = np.frombuffer(data, np.uint8)
        padded = -(-(a.size + self._headroom) // _PAD) * _PAD
        a = np.concatenate([a, np.zeros(padded - a.size, np.uint8)])
        # BE word image on host (cheap vectorized view math); uploaded once
        w = a.reshape(-1, 4).astype(np.uint32)
        words = ((w[:, 0] << 24) | (w[:, 1] << 16) | (w[:, 2] << 8)
                 | w[:, 3])
        img = jax.device_put(words)
        with self._lock:
            # two threads can race the staging above for the same cid; the
            # loser must not double-account the image size (a permanently
            # inflated _used silently shrinks the budget -> early evictions)
            if cid in self._images:
                _M.incr("image_hits")
                return self._images[cid]
            self._used += a.size
            while self._used > self._budget and self._images:
                old_cid = next(iter(self._images))
                self._images.pop(old_cid)
                self._used -= self._sizes.pop(old_cid)
            self._images[cid] = img
            self._sizes[cid] = a.size
            _M.incr("images_staged")
        return img

    def invalidate(self, cid: int) -> None:
        """Container rewritten/compacted: drop the stale image."""
        with self._lock:
            if self._images.pop(cid, None) is not None:
                self._used -= self._sizes.pop(cid, 0)

    def gather(self, wanted: list[tuple[int, int, int]], payload_loader,
               spans: list[tuple[int, int, int]], out: bytearray) -> None:
        """Fill ``out`` per ``spans`` from device-gathered chunk lanes.

        wanted[i] = (container_id, offset, length) of needed chunk i;
        spans[i] = (out_at, lo, n): write chunk i's bytes [lo, lo+n) at
        out[out_at:].  ``payload_loader(cid)`` supplies a container's
        uncompressed payload when its image isn't resident yet."""
        # group by (container, pow2 length bucket): a single max-length
        # bucket would pad every lane to the largest chunk (up to 8x D2H
        # amplification at the measured chunk-size spread)
        groups: dict[tuple[int, int], list[int]] = {}
        for i, (cid, _, ln) in enumerate(wanted):
            b = _bucket_of(-(-ln // 64) + 1)
            groups.setdefault((cid, b), []).append(i)
        for (cid, bucket), idxs in groups.items():
            img = self._image(cid, lambda c=cid: payload_loader(c))
            assert bucket * 64 + 4 <= self._headroom, \
                "chunk larger than the gather headroom"
            L = -(-len(idxs) // 128) * 128
            ol = np.zeros((2, L), np.int32)
            for j, i in enumerate(idxs):
                ol[0, j] = wanted[i][1]
                ol[1, j] = wanted[i][2]
            # Pallas DMA gather on TPU (~0.3 us/lane vs 2-5 us for the
            # vmapped dynamic_slice — the per-lane overhead bound that
            # made the device read path lose even to page-cache host
            # reads, PERF_NOTES.md).  Its tail words carry SHA padding,
            # which is invisible here: spans only read bytes below each
            # chunk's length.  The XLA path remains for CPU and for
            # buckets whose DMA window would run past the image headroom.
            if (jax.default_backend() != "cpu"
                    and bucket * 64 + 640 <= self._headroom):
                from hdrf_tpu.ops.gather_pallas import gather_pad_messages

                lanes = np.asarray(gather_pad_messages(
                    img, jax.device_put(ol), bucket))
                _M.incr("dma_gathers", len(idxs))
            else:
                lanes = np.asarray(gather_lanes_raw(img, jax.device_put(ol),
                                                    bucket))
            lane_bytes = lanes.byteswap().tobytes()  # BE words -> raw bytes
            row = lanes.shape[1] * 4
            for j, i in enumerate(idxs):
                out_at, lo, nb = spans[i]
                base = j * row
                out[out_at:out_at + nb] = \
                    lane_bytes[base + lo:base + lo + nb]
            _M.incr("chunks_gathered", len(idxs))
        _M.incr("reconstructions")
