"""Reed-Solomon erasure coding on the MXU.

The reference's EC layer (client DFSStripedOutputStream.java:81 striping;
DN-side StripedBlockReconstructor fan-in; codecs under Hadoop's native ISA-L
bindings) does GF(2^8) arithmetic byte-at-a-time through lookup tables.  On
TPU, table lookups scalarize — but GF(2^8) multiplication by a *constant* is
linear over GF(2), so a Cauchy-style RS code becomes a 0/1 **bit-matrix
multiply**: expand each k x m GF(256) coefficient into an 8x8 bit matrix,
expand shard bytes into bit planes, and parity = (A @ X) mod 2 — one MXU
matmul over f32 0/1 values (exact: k*8 <= 256 summands < 2^24) plus a cheap
VPU parity reduction.  Decode inverts the surviving rows' GF matrix on the
host (tiny, k x k GF(256)) and runs the same bit-matmul with the inverse.

Layout: X is (k*8, L) — bit b of byte j of shard i at row i*8+b.  Bit planes
are built with broadcasted shifts (no gathers), L stays the minor axis
(lane-friendly), and the matmul's M=m*8, K=k*8 are small so the op is
HBM-bandwidth-bound — the right regime for an erasure code.

Host oracle: `gf_mul`/`encode_ref` implement the same code in numpy GF(2^8)
log/antilog arithmetic; kernels are asserted bit-identical in tests.

Partial-sum repair (the coded-exchange plane, server/coded_exchange.py):
because decode is linear — missing[w] = XOR_j coeff[w,j] * survivor[j] with
coeff = `repair_rows` — each holder can apply ITS columns to ITS stripes
locally (`partial_sums`, one bit-matmul) and ship only the (|want|, L)
contribution; XOR-folding the per-holder contributions reproduces
`rs_decode` bit-identically (GF(2^8) addition IS xor).  This is the
partial-parallel-repair / repair-pipelining shape of the coded-computing
line (arXiv 1802.03049, arXiv 1805.01993), re-expressed over the same
Cauchy bit-matmul as encode; `partial_sums_ref` is the log/antilog oracle
(re-derives DFSStripedOutputStream.java:81's decode split across holders).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

def parse_policy(policy: str) -> tuple[int, int, int]:
    """'rs-6-3-64k' -> (k, m, cell_bytes) (ECPolicyLoader analog)."""
    parts = policy.lower().split("-")
    if len(parts) != 4 or parts[0] != "rs":
        raise ValueError(f"bad EC policy {policy!r} (want rs-<k>-<m>-<cell>k)")
    k, m = int(parts[1]), int(parts[2])
    cell = int(parts[3].rstrip("k")) * 1024
    if not (1 <= k <= 24 and 1 <= m <= 8 and cell > 0):
        raise ValueError(f"bad EC policy {policy!r}")
    return k, m, cell


# --------------------------------------------------------------- GF(2^8) host

_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1 (the usual RS-255 field)


@functools.cache
def _tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _POLY
    exp[255:510] = exp[:255]
    return exp, log


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    exp, log = _tables()
    return int(exp[log[a] + log[b]])


def gf_inv(a: int) -> int:
    exp, log = _tables()
    return int(exp[255 - log[a]])


def gf_mat_inv(m: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix (Gauss-Jordan, host side, tiny)."""
    n = m.shape[0]
    a = m.astype(np.int64).copy()
    inv = np.eye(n, dtype=np.int64)
    for col in range(n):
        piv = next((r for r in range(col, n) if a[r, col]), None)
        if piv is None:
            raise ValueError("singular GF matrix (too many erasures)")
        if piv != col:
            a[[col, piv]] = a[[piv, col]]
            inv[[col, piv]] = inv[[piv, col]]
        pi = gf_inv(int(a[col, col]))
        a[col] = [gf_mul(int(v), pi) for v in a[col]]
        inv[col] = [gf_mul(int(v), pi) for v in inv[col]]
        for r in range(n):
            if r != col and a[r, col]:
                f = int(a[r, col])
                a[r] ^= np.array([gf_mul(f, int(v)) for v in a[col]])
                inv[r] ^= np.array([gf_mul(f, int(v)) for v in inv[col]])
    return inv.astype(np.uint8)


@functools.cache
def rs_matrix(k: int, m: int) -> np.ndarray:
    """(k+m, k) GF(256) generator: identity over data rows + Cauchy parity
    rows 1/(x_i + y_j) — any k rows are invertible (Cauchy property)."""
    g = np.zeros((k + m, k), dtype=np.uint8)
    g[:k] = np.eye(k, dtype=np.uint8)
    xs = list(range(m))           # parity points
    ys = list(range(m, m + k))    # data points; disjoint from xs
    for i in range(m):
        for j in range(k):
            g[k + i, j] = gf_inv(xs[i] ^ ys[j])
    return g


def _bit_matrix(gf_rows: np.ndarray) -> np.ndarray:
    """GF(256) matrix (r, c) -> GF(2) bit matrix (r*8, c*8).

    Row-bit b' of output byte = XOR over input bits b where the bit-matrix
    entry M[b', b] = bit b' of (coeff * x^b) — multiplication by the basis
    monomials.
    """
    r, c = gf_rows.shape
    out = np.zeros((r * 8, c * 8), dtype=np.float32)
    for i in range(r):
        for j in range(c):
            coeff = int(gf_rows[i, j])
            if not coeff:
                continue
            for b in range(8):
                prod = gf_mul(coeff, 1 << b)
                for bp in range(8):
                    if prod >> bp & 1:
                        out[i * 8 + bp, j * 8 + b] = 1.0
    return out


def encode_ref(data: np.ndarray, m: int) -> np.ndarray:
    """Host oracle: parity shards via GF log/antilog table arithmetic.
    data: u8[k, L] -> u8[m, L]."""
    k, L = data.shape
    exp, log = _tables()
    g = rs_matrix(k, m)[k:]
    out = np.zeros((m, L), dtype=np.uint8)
    for i in range(m):
        acc = np.zeros(L, dtype=np.uint8)
        for j in range(k):
            coeff = int(g[i, j])
            if coeff:
                nz = data[j] != 0
                prod = np.zeros(L, dtype=np.uint8)
                prod[nz] = exp[log[coeff] + log[data[j][nz]]]
                acc ^= prod
        out[i] = acc
    return out


# ---------------------------------------------------------------- TPU kernels

@functools.partial(jax.jit, static_argnames=("nrows",))
def _bit_matmul(bitmat: jax.Array, shards: jax.Array, nrows: int) -> jax.Array:
    """(A @ bits(shards)) mod 2, repacked to bytes.

    bitmat: f32[nrows*8, k*8]; shards: u8[k, L] -> u8[nrows, L].
    """
    k, L = shards.shape
    s = shards.astype(jnp.float32)  # one upcast; bit planes by arithmetic
    # bit plane b of shard i: floor(s / 2^b) mod 2 — broadcasted, no gathers
    planes = jnp.stack(
        [jnp.floor(s / float(1 << b)) % 2.0 for b in range(8)], axis=1)
    x = planes.reshape(k * 8, L)
    acc = jnp.dot(bitmat, x, preferred_element_type=jnp.float32)
    bits = acc % 2.0  # XOR = sum mod 2 (exact: <= k*8 summands in f32)
    w = jnp.asarray(
        np.array([1 << b for b in range(8)], dtype=np.float32))
    by = (bits.reshape(nrows, 8, L) * w[None, :, None]).sum(axis=1)
    return by.astype(jnp.uint8)


@functools.cache
def _enc_bitmat(k: int, m: int) -> np.ndarray:
    return _bit_matrix(rs_matrix(k, m)[k:])


def rs_encode(data: bytes | np.ndarray, k: int, m: int) -> np.ndarray:
    """Encode k data shards -> m parity shards on the accelerator.
    data: u8[k, L] (or flat bytes of length k*L)."""
    a = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    shards = a.reshape(k, -1)
    out = _bit_matmul(jnp.asarray(_enc_bitmat(k, m)),
                      jax.device_put(shards), m)
    return np.asarray(out)


@functools.cache
def repair_rows(k: int, m: int, have: tuple[int, ...],
                want: tuple[int, ...]) -> np.ndarray:
    """GF(256) repair matrix R, u8[len(want), k]:
    ``missing[w] = XOR_j gf_mul(R[w, j], survivor[have[j]])``.

    ``have`` names the k survivor indices in use (sorted), ``want`` the
    indices to rebuild (data or parity).  The decode seam shared by
    rs_decode (full gather) and the partial-sum repair plane: each
    holder's contribution applies the COLUMNS of R matching its local
    survivors, so the per-holder split is just column selection."""
    if len(have) != k:
        raise ValueError(f"need {k} survivor indices, got {len(have)}")
    g = rs_matrix(k, m)
    sub = g[list(have)]                 # (k, k) rows that produced survivors
    inv = gf_mat_inv(sub)               # data = inv @ survivors
    rows = np.zeros((len(want), k), dtype=np.uint8)
    for r, idx in enumerate(want):
        if idx < k:
            rows[r] = inv[idx]
        else:  # parity shard: re-encode from decoded data = g[idx] @ inv
            for j in range(k):
                acc = 0
                for t in range(k):
                    acc ^= gf_mul(int(g[idx, t]), int(inv[t, j]))
                rows[r, j] = acc
    return rows


def rs_decode(shards: dict[int, np.ndarray], k: int, m: int,
              want: list[int] | None = None) -> dict[int, np.ndarray]:
    """Recover missing shards from any k survivors.

    shards: {shard_index: u8[L]} with >= k entries (indices 0..k-1 = data,
    k..k+m-1 = parity).  Returns {index: u8[L]} for ``want`` (default: the
    missing data shards).
    """
    have = sorted(shards)[:k]
    if len(have) < k:
        raise ValueError(f"need {k} shards, have {len(have)}")
    if want is None:
        want = [i for i in range(k) if i not in shards]
    if not want:
        return {}
    rows = repair_rows(k, m, tuple(have), tuple(want))
    mat = _bit_matrix(rows)
    surv = np.stack([shards[i] for i in have])
    out = _bit_matmul(jnp.asarray(mat), jax.device_put(surv), len(want))
    out = np.asarray(out)
    return {idx: out[i] for i, idx in enumerate(want)}


def partial_sums(stripes: np.ndarray, coeffs: np.ndarray) -> np.ndarray:
    """One holder's repair contribution: u8[nwant, L] from its LOCAL
    survivor stripes u8[n, L] and its repair_rows column slice
    u8[nwant, n] — a single Cauchy bit-matmul on the accelerator, the
    same kernel encode uses.  XOR-folding every holder's output equals
    ``rs_decode`` of the full gather bit-for-bit."""
    coeffs = np.asarray(coeffs, dtype=np.uint8)
    nwant = coeffs.shape[0]
    out = _bit_matmul(jnp.asarray(_bit_matrix(coeffs)),
                      jax.device_put(np.asarray(stripes)), nwant)
    return np.asarray(out)


def partial_sums_ref(stripes: np.ndarray, coeffs: np.ndarray) -> np.ndarray:
    """Host oracle for ``partial_sums``: GF log/antilog table arithmetic
    (the same tables encode_ref pins against)."""
    stripes = np.asarray(stripes, dtype=np.uint8)
    coeffs = np.asarray(coeffs, dtype=np.uint8)
    nwant, n = coeffs.shape
    L = stripes.shape[1]
    exp, log = _tables()
    out = np.zeros((nwant, L), dtype=np.uint8)
    for w in range(nwant):
        acc = np.zeros(L, dtype=np.uint8)
        for j in range(n):
            c = int(coeffs[w, j])
            if c:
                nz = stripes[j] != 0
                prod = np.zeros(L, dtype=np.uint8)
                prod[nz] = exp[log[c] + log[stripes[j][nz]]]
                acc ^= prod
        out[w] = acc
    return out


def xor_fold(parts: list[np.ndarray]) -> np.ndarray:
    """Accumulate per-holder contributions: GF(2^8) addition is XOR, so
    the fold is associative/commutative — chain order never matters."""
    acc = np.array(parts[0], dtype=np.uint8, copy=True)
    for p in parts[1:]:
        acc ^= np.asarray(p, dtype=np.uint8)
    return acc
