"""Pallas chunk-gather kernel: variable-offset lanes from the HBM word image.

The XLA formulation (vmapped ``dynamic_slice`` + funnel shift in
ops/resident._bucket_sha) pays a fixed ~2-5 us per lane — gather machinery,
not bandwidth — which dominates the reduction pipeline once dispatches are
batched (PERF_NOTES.md).  This kernel replaces it with per-lane async DMAs
at ~0.3 us issue cost each:

1. The word image is viewed as (rows, 128) u32; each lane DMAs the rows
   covering its chunk window (512-byte row granularity, arbitrary row
   offset — probed supported by Mosaic; arbitrary 1D element offsets are
   not).
2. The intra-row word phase (q % 128) is fixed with a dynamic
   ``pltpu.roll`` pair: roll the lane axis by the phase, then select the
   wrapped tail from the next sublane row — a flat left-shift of the
   (rows, 128) window in VPU registers.
3. The byte phase (offset % 4) is a funnel shift of adjacent words, and the
   SHA-256 padding (0x80 marker, zero fill, 64-bit bit length) is spliced
   in the same pass, so the kernel emits ready-to-hash big-endian messages.

Output: (L, ceil(B*16/128)*128) u32 — slice [:, :B*16] feeds
ops/sha256_pallas.sha256_words_pallas unchanged.

Re-expresses the chunk-extraction half of the reference's
DataDeduplicator.java:536-650 (per-chunk array copies feeding the JNI
hasher) as a TPU DMA program.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_TL = 8          # lanes per grid step (one (8,128) u32 tile of out sublanes)
_MAX_LANES = 4096  # per pallas_call: bounds the scalar-prefetch SMEM block


def _flat_shift_dynamic(x, p):
    """Flat left-shift of a (R, 128) window by p words (0 <= p < 128):
    out_flat[i] = x_flat[i + p].  Lane-axis roll + next-sublane carry.
    pltpu.roll requires non-negative shifts, so a left roll by p is a
    right roll by 128 - p (mod the lane count)."""
    y = pltpu.roll(x, (128 - p) % 128, 1)
    carry = pltpu.roll(y, x.shape[0] - 1, 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    return jnp.where(lane < 128 - p, y, carry)


def _flat_shift1(x):
    """Flat left-shift by exactly one word (static)."""
    y = pltpu.roll(x, 127, 1)
    carry = pltpu.roll(y, x.shape[0] - 1, 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    return jnp.where(lane < 127, y, carry)


def _kernel(ol_ref, hbm_ref, out_ref, scratch, sems, *, rw: int):
    t = pl.program_id(0)

    def lane_off(i):
        return ol_ref[0, t * _TL + i]

    for i in range(_TL):
        r0 = lane_off(i) // (4 * 128)
        pltpu.make_async_copy(hbm_ref.at[pl.ds(r0, rw)], scratch.at[i],
                              sems.at[i]).start()
    for i in range(_TL):
        r0 = lane_off(i) // (4 * 128)
        pltpu.make_async_copy(hbm_ref.at[pl.ds(r0, rw)], scratch.at[i],
                              sems.at[i]).wait()
        off = lane_off(i)
        ln = ol_ref[1, t * _TL + i]
        q = off // 4
        p = q % 128                       # word phase within the row
        s8 = ((off % 4) * 8).astype(jnp.uint32)

        a = _flat_shift_dynamic(scratch[i], p)
        b = _flat_shift1(a)
        c = jnp.where(s8 == 0, a,
                      (a << s8) | (b >> (jnp.uint32(32) - s8)))

        # SHA-256 pad splice (same math as resident._bucket_sha, per lane):
        # keep data words, 0x80 marker at byte ``ln``, zero tail, 64-bit
        # big-endian bit length in the final word of the last SHA block.
        wl = ln // 4
        r8 = ((ln % 4) * 8).astype(jnp.uint32)
        j = (jax.lax.broadcasted_iota(jnp.int32, a.shape, 0) * 128
             + jax.lax.broadcasted_iota(jnp.int32, a.shape, 1))
        keep = jnp.where(r8 == jnp.uint32(0), jnp.uint32(0),
                         jnp.uint32(0xFFFFFFFF) << (jnp.uint32(32) - r8))
        marker = jnp.uint32(0x80) << (jnp.uint32(24) - r8)
        boundary = (c & keep) | marker
        msg = jnp.where(j < wl, c,
                        jnp.where(j == wl, boundary, jnp.uint32(0)))
        nb = (ln + 9 + 63) // 64
        last = nb * 16 - 1
        bitlen = (ln * 8).astype(jnp.uint32)
        msg = jnp.where(j == last, bitlen, msg)
        out_ref[i] = msg[: out_ref.shape[1]]


@functools.partial(jax.jit, static_argnames=("bucket",))
def _gather_chunk(words2d: jax.Array, ol: jax.Array, bucket: int):
    L = ol.shape[1]
    w = bucket * 16
    rw = -(-(w + 128) // 128)             # rows covering W+1 words + phase
    out_rows = -(-w // 128)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(L // _TL,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((_TL, out_rows, 128),
                               lambda t, ol_ref: (t, 0, 0)),
        scratch_shapes=[pltpu.VMEM((_TL, rw, 128), jnp.uint32),
                        pltpu.SemaphoreType.DMA((_TL,))],
    )
    fn = pl.pallas_call(
        functools.partial(_kernel, rw=rw),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((L, out_rows, 128), jnp.uint32),
    )
    return fn(ol, words2d).reshape(L, out_rows * 128)


def gather_pad_messages(words: jax.Array, ol: jax.Array,
                        bucket: int) -> jax.Array:
    """(L, bucket*16) u32 SHA-ready big-endian messages for one bucket.

    words: u32[NW] resident flat word image, NW % 128 == 0, zero-padded by
    at least bucket*16 + 160 words past the last addressable offset.
    ol: i32[2, L] — row 0 byte offsets (within the word image), row 1 chunk
    byte lengths.  L % 128 == 0.
    """
    assert words.shape[0] % 128 == 0, "word image must tile into 128-rows"
    words2d = words.reshape(-1, 128)
    L = ol.shape[1]
    w = bucket * 16
    if L <= _MAX_LANES:
        out = _gather_chunk(words2d, ol, bucket)
    else:
        parts = [_gather_chunk(words2d, ol[:, i:i + _MAX_LANES], bucket)
                 for i in range(0, L, _MAX_LANES)]
        out = jnp.concatenate(parts, axis=0)
    return out[:, :w]
