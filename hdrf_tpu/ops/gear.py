"""Gear rolling-hash CDC candidate scan on TPU.

TPU-native reformulation of the reference's sequential byte scan
(DataDeduplicator.chunking(), DataDeduplicator.java:264-307). The sequential
recurrence ``h = (h << 1) + G[b]`` unrolls to a windowed sum

    h[i] = sum_{k=0}^{31} G[b[i-k]] << k   (mod 2^32)

which is computable for *every* position at once by log-doubling: with
``A_m[i] = sum_{k<m} G[b[i-k]] << k`` (window m),

    A_{2m}[i] = A_m[i] + (A_m[i-m] << m)

so five elementwise shift+add+(array roll) steps produce the full window-32
hash for all positions — pure VPU work, no sequential dependence. Candidate
cut-points are positions where ``(h & mask) == 0``; the tiny sequential min/max
selection over the sparse candidates runs on the host (native.cdc_select),
sharing the exact semantics of the CPU baseline (native/src/cdc.cpp).

The gear byte-mixing function is arithmetic — ``G[b] = fmix32(b * 0x9E3779B1)``
(murmur3 finalizer) — rather than a lookup table, because a 256-entry gather
scalarizes on TPU (~10 ns/element, measured), while fmix32 is 6 elementwise VPU
ops across all positions at once. The C++ side (native/src/cdc.cpp) pre-tabulates
the same function; equality is asserted in tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

WINDOW = 32  # bytes contributing to the hash: h[i] covers b[i-31..i]

# Window-warmup convention shared by every candidate producer (this module,
# ops/cdc_pallas.py, native hdrf_gear_candidates): the first WINDOW-1
# positions hold partial-window hashes and can never be cuts, so the
# smallest admissible 1-based cut position is WINDOW.  Pinned by a shared
# test vector in tests/test_cdc_pallas.py.
MIN_CANDIDATE_POS1 = WINDOW


def skip_ahead_threshold(min_chunk: int) -> int:
    """Smallest pos1 a candidate must reach to ever be SELECTABLE under a
    ``min_chunk`` floor.  Every selection window opens at
    ``prev_cut + min_chunk`` (native/src/cdc.cpp:74-92's ``lo``) and
    ``prev_cut >= 0``, so a candidate below
    ``max(MIN_CANDIDATE_POS1, min_chunk)`` is dead on arrival regardless of
    block content.  The skip-ahead kernels (ops/cdc_pallas.py) and the mesh
    plane (parallel/sharded.py) mask such candidates out of candidate
    generation up front — provably cut-identical, because the frontier scan
    could never have picked them.  The XLA scan here stays verbatim: it is
    the all-geometry bit-identity oracle."""
    return max(MIN_CANDIDATE_POS1, int(min_chunk))


def _fmix32_np(z: np.ndarray) -> np.ndarray:
    z = z.astype(np.uint32)
    z ^= z >> np.uint32(16)
    z = (z * np.uint32(0x85EBCA6B)) & np.uint32(0xFFFFFFFF)
    z ^= z >> np.uint32(13)
    z = (z * np.uint32(0xC2B2AE35)) & np.uint32(0xFFFFFFFF)
    z ^= z >> np.uint32(16)
    return z


@functools.cache
def gear_table_np() -> np.ndarray:
    """256-entry uint32 gear table, bit-identical to native hdrf_gear_table()."""
    with np.errstate(over="ignore"):
        return _fmix32_np(np.arange(256, dtype=np.uint32) * np.uint32(0x9E3779B1))


def _gear_map(block_u8: jax.Array) -> jax.Array:
    """G[b] per byte, computed arithmetically (no gather)."""
    z = block_u8.astype(jnp.uint32) * np.uint32(0x9E3779B1)
    z = z ^ (z >> np.uint32(16))
    z = z * np.uint32(0x85EBCA6B)
    z = z ^ (z >> np.uint32(13))
    z = z * np.uint32(0xC2B2AE35)
    z = z ^ (z >> np.uint32(16))
    return z


def _doubling_hashes(t: jax.Array) -> jax.Array:
    """All-position window-32 gear hashes from the mapped byte values ``t``.

    t: uint32[N] where t[i] = G[b[i]]. Returns uint32[N]; positions i < 31 hold
    partial-window values (never used: candidates require p >= 32).
    """
    a = t
    m = 1
    while m < WINDOW:
        # a[i] += a[i-m] << m ; out-of-range reads as 0 (zero-pad shift).
        shifted = jnp.concatenate([jnp.zeros((m,), a.dtype), a[:-m]])
        a = a + (shifted << np.uint32(m))
        m *= 2
    return a


_PACK_ROW = 256  # mask bits packed per matmul row -> 32 output bytes


def candidate_bitmap_words(block_u8: jax.Array, mask: jax.Array,
                           pos1_base: jax.Array | None = None) -> jax.Array:
    """Packed all-position candidate bitmap of a resident block.

    The one implementation of the gear-scan hot path, shared by the
    single-chip scan (_candidate_words), the device-resident pipeline
    (ops/resident._prep), the seq-sharded scan (parallel/sharded), and the
    graft entry.  block_u8: u8[n], n % _PACK_ROW == 0.  ``pos1_base`` offsets
    the 1-based positions for shards of a larger block (uint32 scalar).
    Returns u32[n/32] little-endian bitmap words (bit k of word w = position
    32w + k is a candidate cut *end*, i.e. cut-point = bit index + 1).
    """
    n = block_u8.shape[0]
    t = _gear_map(block_u8)
    h = _doubling_hashes(t)
    pos1 = jnp.arange(1, n + 1, dtype=jnp.uint32)
    if pos1_base is not None:
        pos1 = pos1 + pos1_base
    is_cand = ((h & mask) == 0) & (pos1 >= MIN_CANDIDATE_POS1)
    return pack_bitmap_words(is_cand)


def pack_bitmap_words(is_cand: jax.Array) -> jax.Array:
    """bool[n] -> little-endian u32[n/32] bitmap via the MXU pack matmul
    (exact in f32: per-byte bit sums stay < 2^8).  n % _PACK_ROW == 0."""
    m = is_cand.astype(jnp.float32).reshape(-1, _PACK_ROW)
    packed = jnp.dot(m, jnp.asarray(_pack_weights()),
                     preferred_element_type=jnp.float32)
    # u8 bitcast combine (little-endian), not astype(u32)+strided gather:
    # the (M, 4) u32 intermediate tiles as minor-dim-4 -> 128 lanes (32x
    # memory) when XLA materializes it at batch scale.
    b = packed.astype(jnp.uint8).reshape(-1, 4)
    return jax.lax.bitcast_convert_type(b, jnp.uint32)


@functools.cache
def _pack_weights() -> np.ndarray:
    """Block-diagonal (256, 32) f32: output byte j sums bits 8j..8j+7 weighted
    2^k. Bit sums stay < 2^8 so f32 accumulation is exact; the matmul runs on
    the MXU, which is the fast path for this reduction shape on TPU."""
    w = np.zeros((_PACK_ROW, _PACK_ROW // 8), dtype=np.float32)
    for i in range(_PACK_ROW):
        w[i, i // 8] = float(1 << (i % 8))
    return w


@functools.partial(jax.jit, static_argnames=("cap",))
def _candidate_words(block: jax.Array, mask: jax.Array, cap: int):
    """Sparse candidate bitmap as nonzero u32 words.

    The full bitmap is n/8 bytes — too much for the D2H path (~70 ms fixed +
    ~25 MB/s through the tunnel) — and a flat nonzero over n bools is several
    slow passes. Instead: pack bits to bytes with an MXU matmul (exact in f32),
    combine to u32 words, then nonzero over the n/32 words (sparse at real CDC
    densities). D2H is O(candidates): word indices + word values + count.
    """
    n = block.shape[0]
    pad = (-n) % _PACK_ROW
    words = candidate_bitmap_words(jnp.pad(block, (0, pad)), mask)
    nz = words != 0
    (idx,) = jnp.nonzero(nz, size=cap, fill_value=words.shape[0])
    vals = jnp.take(words, idx, fill_value=0)
    return idx.astype(jnp.uint32), vals, jnp.sum(nz.astype(jnp.int32))


def _words_to_positions(idx: np.ndarray, vals: np.ndarray, n: int) -> np.ndarray:
    """Bit positions from sparse (word_index, word_value) pairs, host side."""
    if idx.size == 0:
        return np.empty(0, dtype=np.uint64)
    # unpackbits over the sparse words only: (k, 32) bits, little-endian.
    bits = np.unpackbits(vals[:, None].astype(">u4").view(np.uint8).reshape(-1, 4)[:, ::-1],
                         axis=1, bitorder="little")
    wi, bi = np.nonzero(bits)
    pos = idx[wi].astype(np.uint64) * 32 + bi + 1  # cut-point = bit index + 1
    pos.sort()
    return pos[pos <= n]


def gear_candidates_jax(data: bytes | np.ndarray, mask: int) -> np.ndarray:
    """Candidate cut-points via the XLA scan; same contract as
    native.gear_candidates."""
    a = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    n = a.size
    if n == 0:
        return np.empty(0, dtype=np.uint64)
    nwords = (n + _PACK_ROW - 1) // _PACK_ROW * (_PACK_ROW // 32)
    density_bits = bin(mask & 0xFFFFFFFF).count("1")
    cap = min(nwords, max(1024, (n >> max(density_bits - 2, 0)) + 1024))
    # device_put streams via DMA; jnp.asarray takes a ~25 MB/s literal path on
    # the tunneled platform (measured ~25x slower for 128 MB).
    block = jax.device_put(a)
    m = jnp.uint32(mask & 0xFFFFFFFF)
    idx, vals, count = _candidate_words(block, m, cap)
    if int(count) > cap:  # dense-candidate retry with exact capacity
        idx, vals, count = _candidate_words(block, m, int(count))
    k = int(count)
    return _words_to_positions(np.asarray(idx)[:k], np.asarray(vals)[:k], n)


def cdc_chunk_jax(data: bytes | np.ndarray, mask: int, min_chunk: int,
                  max_chunk: int) -> np.ndarray:
    """TPU candidate scan + host min/max selection; bit-identical cuts to
    native.cdc_chunk (asserted in tests/test_ops.py)."""
    from hdrf_tpu import native

    a = np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data
    cand = gear_candidates_jax(a, mask)
    return native.cdc_select(cand, a.size, min_chunk, max_chunk)
