"""MiniCluster: in-process NameNode + N DataNodes for tests.

Equivalent of the reference's MiniDFSCluster (MiniDFSCluster.java:141,
3.2 kLoC): boots one real NameNode and N real DataNodes in one process with
per-node data dirs and ephemeral ports, plus restart/kill APIs for failure
testing (restartDataNode/stopDataNode analogs).  Fast config defaults (small
blocks, sub-second heartbeats) keep tests snappy.

``observers=N`` boots N observer NNs per nameservice (read replicas with
bounded staleness, ObserverReadProxyProvider analog) whose addrs join
``nn_addrs()`` — DNs then heartbeat/report to them, keeping their block
maps warm.  ``kill_namenode()``/``restart_namenode()`` mirror the worker
kill/restart knobs, so failover tests and the metadata-storm harness share
one deterministic path.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time

from hdrf_tpu.client.filesystem import HdrfClient
from hdrf_tpu.config import DataNodeConfig, NameNodeConfig
from hdrf_tpu.server.datanode import DataNode
from hdrf_tpu.server.namenode import NameNode


class MiniCluster:
    def __init__(self, n_datanodes: int = 3, base_dir: str | None = None,
                 replication: int = 3, block_size: int = 1 << 20,
                 container_size: int = 1 << 22, heartbeat_s: float = 0.2,
                 dead_node_s: float = 1.5, ha: bool = False,
                 observers: int = 0,
                 journal_nodes: int = 0, secure: bool = False,
                 storage_types: list[str] | None = None,
                 volume_types: list[str] | None = None,
                 nameservices: int = 1,
                 tpu_worker: bool = False,
                 worker_backend: str = "auto",
                 backend: str | None = None,
                 dn_config_overrides: dict | None = None,
                 reduction_overrides: dict | None = None):
        """``journal_nodes`` > 0 boots that many JournalNodes and puts the
        edit log on the quorum (MiniQJMHACluster analog); each NN then gets
        its OWN meta_dir (only the shared-dir deployment shares one).
        ``secure`` turns on the whole security matrix: block tokens,
        delegation-token-authenticated RPCs, and encrypted data transfer.
        ``storage_types`` assigns each DN a StorageType (DISK/SSD/ARCHIVE)
        for storage-policy tests.  ``tpu_worker`` spawns ONE co-located
        reduction-worker PROCESS shared by every DN (the north-star
        out-of-process deployment; backend auto-resolves — native on the
        CPU test mesh, device on a real chip).  ``worker_backend`` pins
        the worker's backend (e.g. ``"tpu"`` to force the jax path on a
        virtual-device mesh); ``backend`` pins the DNs' in-process
        reduction backend (default stays the deterministic native)."""
        self.n_datanodes = n_datanodes
        self.ha = ha
        self.n_journal = journal_nodes
        self.secure = secure
        self.storage_types = storage_types or []
        # per-DN volume types (multi-volume DNs); applies to EVERY DN
        self.volume_types = volume_types
        self.dn_config_overrides = dn_config_overrides or {}
        # knobs applied to every DN's cfg.reduction (deadline/breaker
        # tuning for resilience tests)
        self.reduction_overrides = reduction_overrides or {}
        self.tpu_worker = tpu_worker
        self.worker_backend = worker_backend
        self.backend = backend
        self._worker_proc = None
        self._worker_addr = None
        self._own_dir = base_dir is None
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="hdrf-mini-")
        self.nn_config = NameNodeConfig(
            port=0, meta_dir=os.path.join(self.base_dir, "name"),
            replication=replication, block_size=block_size,
            heartbeat_interval_s=heartbeat_s, dead_node_interval_s=dead_node_s,
            block_tokens=secure, require_token_auth=secure)
        self._dn_kw = dict(container_size=container_size)
        self._heartbeat_s = heartbeat_s
        self.namenode: NameNode | None = None
        self.standby: NameNode | None = None  # MiniQJMHACluster analog
        self.observers_n = observers
        self.observers: list[NameNode] = []   # NS 0's observers
        self._killed: list[NameNode] = []     # abruptly-dead NNs (teardown)
        # Federation (MiniDFSNNTopology analog): ``nameservices`` > 1
        # boots that many independent namespaces over the ONE DN set;
        # each entry of ``self.ns`` is {"active": NN, "standby": NN|None}
        # and NS 0 aliases self.namenode/self.standby.
        self.nameservices_n = nameservices
        assert not (nameservices > 1 and journal_nodes), \
            "per-nameservice journal quorums are not wired in MiniCluster"
        self.ns: list[dict] = []
        self.journalnodes: list = []
        self.datanodes: list[DataNode | None] = [None] * n_datanodes

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "MiniCluster":
        import dataclasses

        if self.tpu_worker:
            from hdrf_tpu.server.reduction_worker import spawn_local_worker

            self._worker_proc, self._worker_addr = spawn_local_worker(
                backend=self.worker_backend)
        if self.n_journal:
            from hdrf_tpu.server.journal import JournalNode

            self.journalnodes = [
                JournalNode(os.path.join(self.base_dir, f"jn{i}")).start()
                for i in range(self.n_journal)]
            self.nn_config = dataclasses.replace(
                self.nn_config,
                meta_dir=os.path.join(self.base_dir, "name-a"),
                journal_addrs=[list(j.addr) for j in self.journalnodes])
        for nsi in range(self.nameservices_n):
            cfg = self.nn_config
            if self.nameservices_n > 1:
                cfg = dataclasses.replace(
                    cfg, nameservice_id=f"ns{nsi}", block_pool_index=nsi,
                    meta_dir=os.path.join(self.base_dir, f"name-ns{nsi}"))
            active = NameNode(cfg).start()
            standby = None
            if self.ha:
                sb_cfg = dataclasses.replace(cfg, role="standby", port=0)
                if self.n_journal:
                    sb_cfg = dataclasses.replace(
                        sb_cfg,
                        meta_dir=os.path.join(self.base_dir,
                                              f"name-b-ns{nsi}"
                                              if self.nameservices_n > 1
                                              else "name-b"),
                        peers=[list(active.addr)])
                standby = NameNode(sb_cfg).start()
                if self.n_journal:
                    # peers must be symmetric: after a failover the DEMOTED
                    # original needs the new active for image bootstrap too
                    active.config.peers = [list(standby.addr)]
            observers = []
            for oi in range(self.observers_n):
                # a snappier tail than the standby default keeps observer
                # staleness (and msync waits) sub-100ms in tests
                ob_cfg = dataclasses.replace(
                    cfg, role="observer", port=0,
                    tail_interval_s=min(cfg.tail_interval_s, 0.05))
                if self.n_journal:
                    ob_cfg = dataclasses.replace(
                        ob_cfg,
                        meta_dir=os.path.join(self.base_dir,
                                              f"name-obs{oi}-ns{nsi}"
                                              if self.nameservices_n > 1
                                              else f"name-obs{oi}"),
                        peers=[list(active.addr)])
                observers.append(NameNode(ob_cfg).start())
            self.ns.append({"active": active, "standby": standby,
                            "observers": observers})
        self.namenode = self.ns[0]["active"]
        self.standby = self.ns[0]["standby"]
        self.observers = self.ns[0]["observers"]
        for i in range(self.n_datanodes):
            self.datanodes[i] = self._make_dn(i).start()
        self.wait_for_datanodes(self.n_datanodes)
        return self

    def stop_journalnode(self, i: int) -> None:
        self.journalnodes[i].stop()

    def nn_addrs(self, nsi: int = 0) -> list:
        """Addrs of ONE nameservice's NNs (active first, then standby,
        then observers — DNs report to all of them; the HA client proxy
        discovers each endpoint's role itself)."""
        ns = self.ns[nsi] if self.ns else {"active": self.namenode,
                                           "standby": self.standby,
                                           "observers": self.observers}
        addrs = [ns["active"].addr] if ns["active"] is not None else []
        if ns["standby"] is not None:
            addrs.append(ns["standby"].addr)
        addrs.extend(o.addr for o in ns.get("observers", []))
        return addrs

    def all_ns_addrs(self) -> list:
        """Nested per-nameservice addr lists (the DN's federation view)."""
        return [self.nn_addrs(i) for i in range(len(self.ns) or 1)]

    def failover(self, nsi: int = 0) -> NameNode:
        """Kill a nameservice's active NN and promote its standby
        (failover drill; other nameservices are untouched)."""
        ns = self.ns[nsi]
        assert ns["standby"] is not None, "not an HA cluster"
        ns["active"].stop()
        ns["standby"].rpc_transition_to_active()
        ns["active"], ns["standby"] = ns["standby"], None
        if nsi == 0:
            self.namenode, self.standby = ns["active"], None
        return ns["active"]

    def _make_dn(self, i: int) -> DataNode:
        cfg = DataNodeConfig(
            port=0, data_dir=os.path.join(self.base_dir, f"dn{i}"),
            heartbeat_interval_s=self._heartbeat_s,
            block_report_interval_s=5.0,
            # tests alias tmp-dir files from anywhere; production keeps the
            # secure default (no mount root = file:// aliasing disabled)
            provided_mount_root="/")
        cfg.reduction.container_size = self._dn_kw["container_size"]
        cfg.reduction.backend = self.backend or "native"  # deterministic
        if self._worker_addr is not None:
            cfg.reduction.worker_addr = list(self._worker_addr)
        cfg.encrypt_data_transfer = self.secure
        if i < len(self.storage_types):
            cfg.storage_type = self.storage_types[i]
        if self.volume_types is not None:
            cfg.volume_types = list(self.volume_types)
        for k, v in self.dn_config_overrides.items():
            setattr(cfg, k, v)
        for k, v in self.reduction_overrides.items():
            setattr(cfg.reduction, k, v)
        addr = (self.all_ns_addrs() if self.nameservices_n > 1
                else self.nn_addrs())
        return DataNode(cfg, addr, dn_id=f"dn-{i}")

    def stop(self) -> None:
        for dn in self.datanodes:
            if dn is not None:
                dn.stop()
        stopped = set()
        for ns in self.ns:
            for nn in [ns["standby"], ns["active"],
                       *ns.get("observers", [])]:
                if nn is not None and id(nn) not in stopped:
                    stopped.add(id(nn))
                    nn.stop()
        for nn in (self.standby, self.namenode, *self.observers):
            if nn is not None and id(nn) not in stopped:
                stopped.add(id(nn))
                nn.stop()
        for nn in self._killed:
            # finish tearing down abruptly-killed NNs (their RPC server is
            # already severed; stop() is idempotent for the rest)
            if id(nn) not in stopped:
                stopped.add(id(nn))
                try:
                    nn.stop()
                except Exception:  # noqa: BLE001 — already half-dead
                    pass
        for jn in self.journalnodes:
            try:
                jn.stop()
            except Exception:  # noqa: BLE001 — may already be stopped
                pass
        if self._worker_proc is not None:
            self._worker_proc.terminate()
            self._worker_proc.wait(timeout=5)
            self._worker_proc = None
        # drop per-edge circuit breakers (process-wide registry): a breaker
        # opened by THIS cluster's faults must not leak into the next test's
        # identically-named dn-N edges
        from hdrf_tpu.utils import retry
        retry.reset_breakers()
        if self._own_dir:
            shutil.rmtree(self.base_dir, ignore_errors=True)
        # reclaim shm segments of RAM_DISK volumes rooted under base_dir
        # (they deliberately survive DN restarts, so sweep by origin)
        import glob
        for marker in glob.glob("/dev/shm/hdrf-ram-*/origin"):
            try:
                with open(marker) as f:
                    if f.read().startswith(
                            os.path.abspath(self.base_dir) + os.sep):
                        shutil.rmtree(os.path.dirname(marker),
                                      ignore_errors=True)
            except OSError:
                pass

    def __enter__(self) -> "MiniCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -------------------------------------------------------- failure APIs

    def stop_datanode(self, i: int) -> None:
        """Clean shutdown (stopDataNode analog)."""
        dn = self.datanodes[i]
        if dn is not None:
            dn.stop()
            self.datanodes[i] = None

    def kill_datanode(self, i: int) -> None:
        """Abrupt death: close sockets without flushing (crash simulation).
        ``_crashed`` is set FIRST so in-flight receivers die without
        touching disk — a dead process cannot finalize partial replicas,
        and a post-kill finalize would race a restarted DN's recovery."""
        dn = self.datanodes[i]
        if dn is not None:
            dn._crashed = True
            dn._stop.set()
            dn._server.shutdown()
            dn._server.server_close()
            dn._sever_connections()
            # in-flight handlers must UNWIND (crashed => no disk writes)
            # before a restart may scan the same directory
            dn.await_xceivers()
            self.datanodes[i] = None

    def kill_worker(self) -> None:
        """SIGKILL the shared reduction worker (kill -9 simulation).  The
        DNs keep its now-dead address: subsequent reduced writes hit
        connection refusals, trip the per-DN worker breaker, and degrade
        to in-process passthrough."""
        assert self._worker_proc is not None, "no tpu_worker in this cluster"
        self._worker_proc.kill()
        self._worker_proc.wait(timeout=5)
        self._worker_proc = None

    def restart_worker(self) -> tuple:
        """Boot a fresh reduction worker (new ephemeral port) and repoint
        every live DN's WorkerClient at it — the out-of-band analog of
        WorkerSupervisor.on_respawn for clusters that own the worker."""
        from hdrf_tpu.server.reduction_worker import spawn_local_worker

        self._worker_proc, self._worker_addr = spawn_local_worker(
            backend=self.worker_backend)
        for dn in self.datanodes:
            if dn is not None and dn._worker is not None:
                dn._worker.set_addr(tuple(self._worker_addr))
                dn.config.reduction.worker_addr = list(self._worker_addr)
        return tuple(self._worker_addr)

    def kill_namenode(self, nsi: int = 0) -> None:
        """Abrupt active-NN death (the kill_datanode/kill_worker idiom for
        the metadata plane): sever the RPC server so clients, DNs and the
        FailoverController all see a dead endpoint — no clean editlog
        close, no role handoff.  Promotion is the controller's job; full
        teardown of the corpse happens at cluster stop()."""
        ns = self.ns[nsi]
        nn = ns["active"]
        assert nn is not None, "active namenode already dead"
        nn._monitor_stop.set()
        nn._rpc.stop()
        self._killed.append(nn)
        ns["active"] = None
        if nsi == 0:
            self.namenode = None

    def restart_namenode(self) -> NameNode:
        """Stop + boot the NameNode over the same meta dir AND the same port
        (so running DNs/clients reconnect) — exercises fsimage+edits recovery.
        After kill_namenode() this reboots the corpse's config; if a
        controller promoted a standby meanwhile, the reboot comes back,
        claims the next epoch on transition only — here it restarts as
        active and the journal-epoch fencing settles who wins."""
        import dataclasses

        prev = self.namenode if self.namenode is not None else self._killed[-1]
        port = prev.addr[1]
        # the RUNNING NN's config, not the base template: with federation
        # ns0's meta_dir/identity were set by dataclasses.replace at start
        # role is forced active: a promoted ex-standby's CONFIG still says
        # standby (transition_to_active flips the runtime role only), and
        # restarting it as a standby would leave the cluster activeless
        cfg = dataclasses.replace(prev.config, port=port, role="active")
        if self.namenode is not None:
            self.namenode.stop()
        self.namenode = NameNode(cfg).start()
        if self.ns:
            self.ns[0]["active"] = self.namenode
        return self.namenode

    def restart_datanode(self, i: int) -> DataNode:
        """Boot a DN over the same data dir (restartDataNode analog) —
        exercises replica/index recovery."""
        assert self.datanodes[i] is None, f"dn{i} still running"
        self.datanodes[i] = self._make_dn(i).start()
        return self.datanodes[i]

    # ------------------------------------------------------------- helpers

    def client(self, name: str | None = None, nsi: int = 0) -> HdrfClient:
        """A client of ONE nameservice (federation clients mount specific
        namespaces, viewfs-style; there is no cross-NS client view)."""
        from hdrf_tpu.config import ClientConfig

        addrs = self.nn_addrs(nsi)
        cfg = ClientConfig(encrypt_data_transfer=self.secure,
                           use_delegation_tokens=self.secure)
        return HdrfClient(addrs if len(addrs) > 1 else addrs[0], name=name,
                          config=cfg)

    def wait_for_datanodes(self, n: int, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        with self.client("minicluster-probe") as c:
            while time.monotonic() < deadline:
                live = [d for d in c.datanode_report() if d["alive"]]
                if len(live) >= n:
                    return
                time.sleep(0.05)
        raise TimeoutError(f"{n} datanodes not live within {timeout}s")

    def wait_for_replication(self, path: str, want: int,
                             timeout: float = 15.0) -> None:
        """Block until every block of ``path`` has >= want live locations."""
        deadline = time.monotonic() + timeout
        with self.client("minicluster-probe") as c:
            while time.monotonic() < deadline:
                loc = c._nn.call("get_block_locations", path=path)
                if loc["blocks"] and all(len(b["locations"]) >= want
                                         for b in loc["blocks"]):
                    return
                time.sleep(0.1)
        raise TimeoutError(f"{path} not replicated to {want} within {timeout}s")
