"""ctypes bindings to libhdrf_native.so.

The native library plays the role of the reference's native layer:
libnayuki-native-hashes.so (JNI SHA, utilities.java:98-137), JNI codec backends
(snappy-java / hadoop-lzo), and the hot CDC scan loop
(DataDeduplicator.chunking(), DataDeduplicator.java:264-307).

Built on demand from ``src/*.cpp`` with g++ if the .so is missing or stale —
the moral equivalent of the reference installing its prebuilt jar from
``hadoop-hdfs/pom.xml:245-255``, but from source.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libhdrf_native.so")

_lib: ctypes.CDLL | None = None
_lock = threading.Lock()

_u8p = ctypes.POINTER(ctypes.c_uint8)
_i32p = ctypes.POINTER(ctypes.c_int32)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_u64p = ctypes.POINTER(ctypes.c_uint64)


def _build() -> None:
    subprocess.run(["make", "-s", "-C", _DIR], check=True,
                   capture_output=True, text=True)


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None:
            return _lib
        srcs = [os.path.join(_DIR, "src", f) for f in os.listdir(os.path.join(_DIR, "src"))
                if f.endswith(".cpp")]
        if not os.path.exists(_SO) or any(os.path.getmtime(s) > os.path.getmtime(_SO)
                                          for s in srcs):
            _build()
        lib = ctypes.CDLL(_SO)

        lib.hdrf_sha256.argtypes = [_u8p, ctypes.c_uint64, _u8p]
        lib.hdrf_sha256_batch.argtypes = [_u8p, _u64p, _u64p, ctypes.c_uint64, _u8p]
        lib.hdrf_gear_table.argtypes = [_u32p]
        lib.hdrf_gear_candidates.argtypes = [_u8p, ctypes.c_uint64, ctypes.c_uint32,
                                             _u64p, ctypes.c_uint64]
        lib.hdrf_gear_candidates.restype = ctypes.c_uint64
        lib.hdrf_cdc_select.argtypes = [_u64p, ctypes.c_uint64, ctypes.c_uint64,
                                        ctypes.c_uint64, ctypes.c_uint64, _u64p,
                                        ctypes.c_uint64]
        lib.hdrf_cdc_select.restype = ctypes.c_uint64
        lib.hdrf_cdc_chunk.argtypes = [_u8p, ctypes.c_uint64, ctypes.c_uint32,
                                       ctypes.c_uint64, ctypes.c_uint64, _u64p,
                                       ctypes.c_uint64]
        lib.hdrf_cdc_chunk.restype = ctypes.c_uint64
        lib.hdrf_lz4_compress_bound.argtypes = [ctypes.c_uint64]
        lib.hdrf_lz4_compress_bound.restype = ctypes.c_uint64
        lib.hdrf_lz4_compress.argtypes = [_u8p, ctypes.c_uint64, _u8p, ctypes.c_uint64]
        lib.hdrf_lz4_compress.restype = ctypes.c_uint64
        lib.hdrf_lz4_compress_tail.argtypes = [
            _u8p, ctypes.c_uint64, _u8p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64)]
        lib.hdrf_lz4_compress_tail.restype = ctypes.c_uint64
        lib.hdrf_lz4_decompress.argtypes = [_u8p, ctypes.c_uint64, _u8p, ctypes.c_uint64]
        lib.hdrf_lz4_decompress.restype = ctypes.c_uint64
        lib.hdrf_lz4_unpack_records.argtypes = [
            _u32p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, _i32p, _u32p]
        lib.hdrf_lz4_unpack_records.restype = ctypes.c_uint64
        lib.hdrf_lz4_emit.argtypes = [_u8p, ctypes.c_uint64, _i32p, _u32p,
                                      ctypes.c_uint64, _u8p, ctypes.c_uint64]
        lib.hdrf_lz4_emit.restype = ctypes.c_uint64
        lib.hdrf_crc32c.argtypes = [ctypes.c_uint32, _u8p, ctypes.c_uint64]
        lib.hdrf_crc32c.restype = ctypes.c_uint32
        lib.hdrf_chacha20_xor.argtypes = [_u8p, _u8p, ctypes.c_uint32, _u8p,
                                          ctypes.c_uint64, _u8p]
        lib.hdrf_aead_seal.argtypes = [_u8p, _u8p, _u8p, ctypes.c_uint64,
                                       _u8p, ctypes.c_uint64, _u8p]
        lib.hdrf_aead_open.argtypes = [_u8p, _u8p, _u8p, ctypes.c_uint64,
                                       _u8p, ctypes.c_uint64, _u8p]
        lib.hdrf_aead_open.restype = ctypes.c_int
        lib.hdrf_crc32c_chunks.argtypes = [_u8p, ctypes.c_uint64, ctypes.c_uint64, _u32p]
        lib.hdrf_gather_ranges.argtypes = [_u8p, ctypes.c_uint64, _u64p,
                                           _u64p, _u8p]
        lib.hdrf_gather_ranges.restype = ctypes.c_uint64
        _lib = lib
        return lib


def _as_u8(buf: bytes | bytearray | memoryview | np.ndarray) -> np.ndarray:
    if isinstance(buf, np.ndarray):
        if buf.dtype != np.uint8 or not buf.flags.c_contiguous:
            raise ValueError("expected C-contiguous uint8 array")
        return buf
    return np.frombuffer(buf, dtype=np.uint8)


def _ptr(a: np.ndarray, typ):  # noqa: ANN001
    return a.ctypes.data_as(typ)


# ---------------------------------------------------------------- public API


def sha256(data: bytes | np.ndarray) -> bytes:
    a = _as_u8(data)
    out = np.empty(32, dtype=np.uint8)
    _load().hdrf_sha256(_ptr(a, _u8p), a.size, _ptr(out, _u8p))
    return out.tobytes()


def sha256_batch(data: bytes | np.ndarray, offsets: np.ndarray,
                 lengths: np.ndarray) -> np.ndarray:
    """Hash n sub-ranges of `data`; returns (n, 32) uint8 digests."""
    a = _as_u8(data)
    offs = np.ascontiguousarray(offsets, dtype=np.uint64)
    lens = np.ascontiguousarray(lengths, dtype=np.uint64)
    if offs.shape != lens.shape:
        raise ValueError("offsets/lengths shape mismatch")
    if offs.size and int((offs + lens).max()) > a.size:
        raise ValueError("chunk range exceeds data buffer")
    n = offs.size
    out = np.empty((n, 32), dtype=np.uint8)
    _load().hdrf_sha256_batch(_ptr(a, _u8p), _ptr(offs, _u64p), _ptr(lens, _u64p),
                              n, _ptr(out, _u8p))
    return out


def gear_table() -> np.ndarray:
    out = np.empty(256, dtype=np.uint32)
    _load().hdrf_gear_table(_ptr(out, _u32p))
    return out


def gear_candidates(data: bytes | np.ndarray, mask: int) -> np.ndarray:
    a = _as_u8(data)
    cap = max(a.size // 8, 1024)
    out = np.empty(cap, dtype=np.uint64)
    n = _load().hdrf_gear_candidates(_ptr(a, _u8p), a.size, mask & 0xFFFFFFFF,
                                     _ptr(out, _u64p), cap)
    if n > cap:  # dense-candidate mask (few effective bits): retry exact-sized
        out = np.empty(n, dtype=np.uint64)
        n = _load().hdrf_gear_candidates(_ptr(a, _u8p), a.size, mask & 0xFFFFFFFF,
                                         _ptr(out, _u64p), n)
    return out[:n].copy()


def cdc_select(candidates: np.ndarray, length: int, min_chunk: int,
               max_chunk: int) -> np.ndarray:
    cand = np.ascontiguousarray(candidates, dtype=np.uint64)
    cap = length // max(min_chunk, 1) + 2
    out = np.empty(cap, dtype=np.uint64)
    n = _load().hdrf_cdc_select(_ptr(cand, _u64p), cand.size, length, min_chunk,
                                max_chunk, _ptr(out, _u64p), cap)
    return out[:n].copy()


def cdc_chunk(data: bytes | np.ndarray, mask: int, min_chunk: int,
              max_chunk: int) -> np.ndarray:
    """Sequential CPU chunker: cut-points (exclusive ends) for the whole buffer."""
    a = _as_u8(data)
    cap = a.size // max(min_chunk, 1) + 2
    out = np.empty(cap, dtype=np.uint64)
    n = _load().hdrf_cdc_chunk(_ptr(a, _u8p), a.size, mask & 0xFFFFFFFF, min_chunk,
                               max_chunk, _ptr(out, _u64p), cap)
    return out[:n].copy()


def lz4_compress(data: bytes | np.ndarray) -> bytes:
    a = _as_u8(data)
    if a.size == 0:
        return b""
    cap = _load().hdrf_lz4_compress_bound(a.size)
    out = np.empty(cap, dtype=np.uint8)
    n = _load().hdrf_lz4_compress(_ptr(a, _u8p), a.size, _ptr(out, _u8p), cap)
    if n == 0:
        raise RuntimeError("lz4 compression failed")
    return out[:n].tobytes()


def lz4_compress_tail(data: bytes | np.ndarray) -> tuple[bytes, int, int]:
    """lz4_compress plus (tail_token_offset, tail_literal_count) of the
    stream's final literals-only sequence — what the parallel segmented
    compressor's stitcher needs (ops/lz4_tpu.lz4_stitch)."""
    a = _as_u8(data)
    if a.size == 0:
        return b"", 0, 0
    cap = _load().hdrf_lz4_compress_bound(a.size)
    out = np.empty(cap, dtype=np.uint8)
    toff = ctypes.c_uint64()
    tlit = ctypes.c_uint64()
    n = _load().hdrf_lz4_compress_tail(_ptr(a, _u8p), a.size, _ptr(out, _u8p),
                                       cap, ctypes.byref(toff),
                                       ctypes.byref(tlit))
    if n == 0:
        raise RuntimeError("lz4 compression failed")
    return out[:n].tobytes(), toff.value, tlit.value


def lz4_emit(data: bytes | np.ndarray, positions: np.ndarray,
             delta_len: np.ndarray) -> bytes:
    """Greedy-parse + serialize an LZ4 block from externally discovered match
    records (the host half of the TPU LZ4 path; see hdrf_lz4_emit).  Records
    are (position, (offset << 16) | est_len), sorted by position."""
    a = _as_u8(data)
    if a.size == 0:
        return b""
    ps = np.ascontiguousarray(positions, dtype=np.int32)
    dl = np.ascontiguousarray(delta_len, dtype=np.uint32)
    if ps.shape != dl.shape:
        raise ValueError("positions/delta_len shape mismatch")
    cap = _load().hdrf_lz4_compress_bound(a.size)
    out = np.empty(cap, dtype=np.uint8)
    n = _load().hdrf_lz4_emit(_ptr(a, _u8p), a.size, _ptr(ps, _i32p),
                              _ptr(dl, _u32p), ps.size, _ptr(out, _u8p), cap)
    if n == 0:
        raise RuntimeError("lz4 emit failed")
    return out[:n].tobytes()


def lz4_unpack_records(row: np.ndarray, p3: int, nv: int, stride: int,
                       esc_slots: int):
    """Decode the packed device record readback (see hdrf_lz4_unpack_records
    and the ops/lz4_tpu._match_scan_impl layout docstring) into the
    (positions, (offset << 16) | len) arrays lz4_emit consumes.  ``row`` is
    the u32 body AFTER the 4-word header.  Returns (pos i32[nrec],
    dl u32[nrec], nrec); nrec < nv means an escape lane overflowed on
    device and the tail was not decodable."""
    r = np.ascontiguousarray(row, dtype=np.uint32)
    if r.size < p3 + p3 // 4 + 2 * esc_slots:
        raise ValueError("packed record row too short")
    if not 0 <= nv <= p3:
        raise ValueError("invalid record count")
    pos = np.empty(nv, dtype=np.int32)
    dl = np.empty(nv, dtype=np.uint32)
    nrec = _load().hdrf_lz4_unpack_records(
        _ptr(r, _u32p), p3, nv, stride, esc_slots,
        _ptr(pos, _i32p), _ptr(dl, _u32p))
    return pos[:nrec], dl[:nrec], int(nrec)


def lz4_decompress(data: bytes | np.ndarray, decompressed_size: int) -> bytes:
    a = _as_u8(data)
    if decompressed_size == 0:
        return b""
    out = np.empty(decompressed_size, dtype=np.uint8)
    n = _load().hdrf_lz4_decompress(_ptr(a, _u8p), a.size, _ptr(out, _u8p),
                                    decompressed_size)
    if n != decompressed_size:
        raise RuntimeError(f"lz4 decompression failed: got {n}, want {decompressed_size}")
    return out.tobytes()


def chacha20_xor(key: bytes, nonce: bytes, data: bytes | np.ndarray,
                 counter: int = 1) -> bytes:
    """Raw ChaCha20 keystream XOR (RFC 8439)."""
    assert len(key) == 32 and len(nonce) == 12
    a = _as_u8(data)
    out = np.empty(a.size, dtype=np.uint8)
    _load().hdrf_chacha20_xor(_ptr(np.frombuffer(key, np.uint8), _u8p),
                              _ptr(np.frombuffer(nonce, np.uint8), _u8p),
                              counter, _ptr(a, _u8p), a.size, _ptr(out, _u8p))
    return out.tobytes()


def aead_seal(key: bytes, nonce: bytes, aad: bytes,
              plaintext: bytes | np.ndarray) -> bytes:
    """ChaCha20-Poly1305 seal: ciphertext || 16-byte tag."""
    assert len(key) == 32 and len(nonce) == 12
    a = _as_u8(plaintext)
    ad = np.frombuffer(aad, np.uint8) if aad else np.empty(0, np.uint8)
    out = np.empty(a.size + 16, dtype=np.uint8)
    _load().hdrf_aead_seal(_ptr(np.frombuffer(key, np.uint8), _u8p),
                           _ptr(np.frombuffer(nonce, np.uint8), _u8p),
                           _ptr(ad, _u8p), ad.size, _ptr(a, _u8p), a.size,
                           _ptr(out, _u8p))
    return out.tobytes()


def aead_open(key: bytes, nonce: bytes, aad: bytes,
              sealed: bytes | np.ndarray) -> bytes | None:
    """ChaCha20-Poly1305 open; None if authentication fails."""
    assert len(key) == 32 and len(nonce) == 12
    a = _as_u8(sealed)
    if a.size < 16:
        return None
    ad = np.frombuffer(aad, np.uint8) if aad else np.empty(0, np.uint8)
    out = np.empty(a.size - 16, dtype=np.uint8)
    ok = _load().hdrf_aead_open(_ptr(np.frombuffer(key, np.uint8), _u8p),
                                _ptr(np.frombuffer(nonce, np.uint8), _u8p),
                                _ptr(ad, _u8p), ad.size, _ptr(a, _u8p),
                                a.size - 16, _ptr(out, _u8p))
    return out.tobytes() if ok else None


def crc32c(data: bytes | np.ndarray, crc: int = 0) -> int:
    a = _as_u8(data)
    return _load().hdrf_crc32c(crc & 0xFFFFFFFF, _ptr(a, _u8p), a.size)


def crc32c_chunks(data: bytes | np.ndarray, chunk_size: int) -> np.ndarray:
    a = _as_u8(data)
    n = (a.size + chunk_size - 1) // chunk_size
    out = np.empty(max(n, 1), dtype=np.uint32)
    _load().hdrf_crc32c_chunks(_ptr(a, _u8p), a.size, chunk_size, _ptr(out, _u32p))
    return out[:n]


def gather_ranges(data: bytes | np.ndarray, starts: np.ndarray,
                  lens: np.ndarray) -> np.ndarray:
    """Concatenate [start, start+len) ranges of ``data`` into one buffer —
    the commit path's chunk-byte shuffle (threadedStorer's per-chunk
    ByteBuffer copies, DataDeduplicator.java:652-845) in one native pass."""
    a = _as_u8(data)
    ss = np.ascontiguousarray(starts, dtype=np.uint64)
    ls = np.ascontiguousarray(lens, dtype=np.uint64)
    if ss.shape != ls.shape:
        raise ValueError("starts/lens shape mismatch")
    if ss.size and int((ss + ls).max()) > a.size:
        raise ValueError("range exceeds data buffer")
    out = np.empty(int(ls.sum()), dtype=np.uint8)
    _load().hdrf_gather_ranges(_ptr(a, _u8p), ss.size, _ptr(ss, _u64p),
                               _ptr(ls, _u64p), _ptr(out, _u8p))
    return out
