// LZ4 block-format codec, implemented from scratch.
//
// Role-equivalent of the reference's JNI codec backends (snappy-java / hadoop-lzo /
// Hadoop Lz4 reached from BlockReceiver.java:822-866 and the container rollover
// compression at DataDeduplicator.java:770-781). Standard LZ4 block format:
// sequences of [token][lit-len ext*][literals][offset u16le][match-len ext*],
// minimum match 4, last sequence is literals-only.

#include <cstdint>
#include <cstring>

namespace {

constexpr int MIN_MATCH = 4;
constexpr int HASH_LOG = 16;
constexpr int LAST_LITERALS = 5;   // spec: last 5 bytes are always literals
constexpr int MFLIMIT = 12;        // spec: no match may start within last 12 bytes

inline uint32_t read32(const uint8_t *p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

inline uint32_t hash4(uint32_t v) {
  return (v * 2654435761u) >> (32 - HASH_LOG);
}

// Write a length with 255-run extension bytes.
inline uint8_t *write_len_ext(uint8_t *op, uint64_t len) {
  while (len >= 255) { *op++ = 255; len -= 255; }
  *op++ = uint8_t(len);
  return op;
}

}  // namespace

extern "C" {

uint64_t hdrf_lz4_compress_bound(uint64_t n) { return n + n / 255 + 16; }

// Returns compressed size, or 0 if dst is too small / input empty.
uint64_t hdrf_lz4_compress(const uint8_t *src, uint64_t srclen, uint8_t *dst,
                           uint64_t dstcap) {
  if (srclen == 0 || dstcap < hdrf_lz4_compress_bound(srclen)) return 0;
  static thread_local uint32_t table[1 << HASH_LOG];
  memset(table, 0, sizeof(table));

  const uint8_t *ip = src;
  const uint8_t *anchor = src;
  const uint8_t *iend = src + srclen;
  const uint8_t *mflimit = srclen > MFLIMIT ? iend - MFLIMIT : src;
  uint8_t *op = dst;

  if (srclen > MFLIMIT) {
    table[hash4(read32(ip))] = 0;
    ip++;
    while (ip < mflimit) {
      // Find a match via the 4-byte hash table.
      uint32_t h = hash4(read32(ip));
      const uint8_t *ref = src + table[h];
      table[h] = uint32_t(ip - src);
      if (ref >= ip || ip - ref > 65535 || read32(ref) != read32(ip)) {
        ip++;
        continue;
      }
      // Extend the match backward over pending literals.
      while (ip > anchor && ref > src && ip[-1] == ref[-1]) { ip--; ref--; }
      // Extend forward (must leave LAST_LITERALS at the tail).
      const uint8_t *matchlimit = iend - LAST_LITERALS;
      const uint8_t *mip = ip + MIN_MATCH;
      const uint8_t *mref = ref + MIN_MATCH;
      while (mip < matchlimit && *mip == *mref) { mip++; mref++; }
      uint64_t matchlen = uint64_t(mip - ip);
      uint64_t litlen = uint64_t(ip - anchor);

      // Token + literal run.
      uint8_t *token = op++;
      if (litlen >= 15) {
        *token = 0xF0;
        op = write_len_ext(op, litlen - 15);
      } else {
        *token = uint8_t(litlen << 4);
      }
      memcpy(op, anchor, litlen);
      op += litlen;
      // Offset + match length.
      uint16_t off = uint16_t(ip - ref);
      *op++ = uint8_t(off);
      *op++ = uint8_t(off >> 8);
      uint64_t mlcode = matchlen - MIN_MATCH;
      if (mlcode >= 15) {
        *token |= 0x0F;
        op = write_len_ext(op, mlcode - 15);
      } else {
        *token |= uint8_t(mlcode);
      }
      ip = mip;
      anchor = ip;
      if (ip < mflimit) table[hash4(read32(ip))] = uint32_t(ip - src);
    }
  }

  // Final literals-only sequence.
  uint64_t litlen = uint64_t(iend - anchor);
  uint8_t *token = op++;
  if (litlen >= 15) {
    *token = 0xF0;
    op = write_len_ext(op, litlen - 15);
  } else {
    *token = uint8_t(litlen << 4);
  }
  memcpy(op, anchor, litlen);
  op += litlen;
  return uint64_t(op - dst);
}

// Returns decompressed size, or 0 on malformed input / overflow.
uint64_t hdrf_lz4_decompress(const uint8_t *src, uint64_t srclen, uint8_t *dst,
                             uint64_t dstcap) {
  const uint8_t *ip = src, *iend = src + srclen;
  uint8_t *op = dst, *oend = dst + dstcap;
  while (ip < iend) {
    uint8_t token = *ip++;
    // Literals.
    uint64_t litlen = token >> 4;
    if (litlen == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return 0;
        b = *ip++;
        litlen += b;
      } while (b == 255);
    }
    if (uint64_t(iend - ip) < litlen || uint64_t(oend - op) < litlen) return 0;
    memcpy(op, ip, litlen);
    ip += litlen;
    op += litlen;
    if (ip == iend) break;  // last sequence has no match part
    // Match.
    if (iend - ip < 2) return 0;
    uint32_t offset = uint32_t(ip[0]) | (uint32_t(ip[1]) << 8);
    ip += 2;
    if (offset == 0 || offset > uint64_t(op - dst)) return 0;
    uint64_t matchlen = (token & 0x0F);
    if (matchlen == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return 0;
        b = *ip++;
        matchlen += b;
      } while (b == 255);
    }
    matchlen += MIN_MATCH;
    if (uint64_t(oend - op) < matchlen) return 0;
    const uint8_t *match = op - offset;
    // Byte-wise copy: offsets < matchlen intentionally replicate (RLE).
    for (uint64_t i = 0; i < matchlen; i++) op[i] = match[i];
    op += matchlen;
  }
  return uint64_t(op - dst);
}

}  // extern "C"
