// LZ4 block-format codec, implemented from scratch.
//
// Role-equivalent of the reference's JNI codec backends (snappy-java / hadoop-lzo /
// Hadoop Lz4 reached from BlockReceiver.java:822-866 and the container rollover
// compression at DataDeduplicator.java:770-781). Standard LZ4 block format:
// sequences of [token][lit-len ext*][literals][offset u16le][match-len ext*],
// minimum match 4, last sequence is literals-only.

#include <cstdint>
#include <cstring>

namespace {

constexpr int MIN_MATCH = 4;
constexpr int HASH_LOG = 16;
constexpr int LAST_LITERALS = 5;   // spec: last 5 bytes are always literals
constexpr int MFLIMIT = 12;        // spec: no match may start within last 12 bytes

inline uint32_t read32(const uint8_t *p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

inline uint32_t hash4(uint32_t v) {
  return (v * 2654435761u) >> (32 - HASH_LOG);
}

inline uint64_t read64(const uint8_t *p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;
}

// First p in [p, lim) with read32(p) == read32(p - off), else nullptr.
// Word-at-a-time: one 8-byte XOR covers match starts p..p+4 (the zero-byte
// mask trick finds 4 consecutive equal bytes), ~5x fewer loads than the
// byte loop it replaces.  Caller guarantees p >= src+off and
// lim <= iend - MFLIMIT, so the 8-byte loads never pass the buffer end.
inline const uint8_t *scan_eq4(const uint8_t *p, const uint8_t *lim,
                               uint32_t off) {
  constexpr uint64_t LO7 = 0x7F7F7F7F7F7F7F7FULL;
  while (p + 5 <= lim) {
    uint64_t d = read64(p) ^ read64(p - off);
    if (d == 0) return p;
    // byte i equal <=> byte i of d zero; need 4 consecutive zero bytes.
    // EXACT per-byte zero mask: additions are confined to the low 7 bits
    // of each byte, so no cross-byte borrow can flag a non-zero byte
    // (the classic (d-0x01..)&~d&0x80.. trick is NOT per-byte exact —
    // its borrow propagates past a true zero byte and falsely flags
    // 0x01 bytes above it, which emitted corrupt matches).
    uint64_t t = (d & LO7) + LO7;
    uint64_t z = ~(t | d | LO7);                // bit 8i+7 = byte i zero
    uint64_t zb = z >> 7;                       // bit 8i   = byte i zero
    uint64_t m = zb & (zb >> 8) & (zb >> 16) & (zb >> 24);
    if (m) return p + (__builtin_ctzll(m) >> 3);
    p += 5;
  }
  for (; p < lim; p++)
    if (read32(p) == read32(p - off)) return p;
  return nullptr;
}

// Write a length with 255-run extension bytes.
inline uint8_t *write_len_ext(uint8_t *op, uint64_t len) {
  while (len >= 255) { *op++ = 255; len -= 255; }
  *op++ = uint8_t(len);
  return op;
}

}  // namespace

extern "C" {

uint64_t hdrf_lz4_compress_bound(uint64_t n) { return n + n / 255 + 16; }

// Returns compressed size, or 0 if dst is too small / input empty.
uint64_t hdrf_lz4_compress(const uint8_t *src, uint64_t srclen, uint8_t *dst,
                           uint64_t dstcap) {
  if (srclen == 0 || dstcap < hdrf_lz4_compress_bound(srclen)) return 0;
  static thread_local uint32_t table[1 << HASH_LOG];
  memset(table, 0, sizeof(table));

  const uint8_t *ip = src;
  const uint8_t *anchor = src;
  const uint8_t *iend = src + srclen;
  const uint8_t *mflimit = srclen > MFLIMIT ? iend - MFLIMIT : src;
  uint8_t *op = dst;

  if (srclen > MFLIMIT) {
    table[hash4(read32(ip))] = 0;
    ip++;
    while (ip < mflimit) {
      // Find a match via the 4-byte hash table.
      uint32_t h = hash4(read32(ip));
      const uint8_t *ref = src + table[h];
      table[h] = uint32_t(ip - src);
      if (ref >= ip || ip - ref > 65535 || read32(ref) != read32(ip)) {
        ip++;
        continue;
      }
      // Extend the match backward over pending literals.
      while (ip > anchor && ref > src && ip[-1] == ref[-1]) { ip--; ref--; }
      // Extend forward (must leave LAST_LITERALS at the tail).
      const uint8_t *matchlimit = iend - LAST_LITERALS;
      const uint8_t *mip = ip + MIN_MATCH;
      const uint8_t *mref = ref + MIN_MATCH;
      while (mip < matchlimit && *mip == *mref) { mip++; mref++; }
      uint64_t matchlen = uint64_t(mip - ip);
      uint64_t litlen = uint64_t(ip - anchor);

      // Token + literal run.
      uint8_t *token = op++;
      if (litlen >= 15) {
        *token = 0xF0;
        op = write_len_ext(op, litlen - 15);
      } else {
        *token = uint8_t(litlen << 4);
      }
      memcpy(op, anchor, litlen);
      op += litlen;
      // Offset + match length.
      uint16_t off = uint16_t(ip - ref);
      *op++ = uint8_t(off);
      *op++ = uint8_t(off >> 8);
      uint64_t mlcode = matchlen - MIN_MATCH;
      if (mlcode >= 15) {
        *token |= 0x0F;
        op = write_len_ext(op, mlcode - 15);
      } else {
        *token |= uint8_t(mlcode);
      }
      ip = mip;
      anchor = ip;
      if (ip < mflimit) table[hash4(read32(ip))] = uint32_t(ip - src);
    }
  }

  // Final literals-only sequence.
  uint64_t litlen = uint64_t(iend - anchor);
  uint8_t *token = op++;
  if (litlen >= 15) {
    *token = 0xF0;
    op = write_len_ext(op, litlen - 15);
  } else {
    *token = uint8_t(litlen << 4);
  }
  memcpy(op, anchor, litlen);
  op += litlen;
  return uint64_t(op - dst);
}

// hdrf_lz4_compress + tail-sequence report, for parallel segmented
// compression (ops/lz4_tpu._lz4_compress_parallel): segments compress
// independently and are STITCHED into one spec-valid block stream by
// merging each junction's literal tail into the next segment's first
// sequence.  The stitcher needs to know where this stream's final
// (literals-only) sequence begins and how many literals it carries —
// information only the encoder has (the block format has no end marker;
// the tail is recognized purely by reaching end-of-input).
uint64_t hdrf_lz4_compress_tail(const uint8_t *src, uint64_t srclen,
                                uint8_t *dst, uint64_t dstcap,
                                uint64_t *tail_off, uint64_t *tail_lit) {
  uint64_t n = hdrf_lz4_compress(src, srclen, dst, dstcap);
  if (n == 0) return 0;
  // Walk the sequences to the last one.  O(sequences), no byte copying;
  // done here (not in the encoder body) to keep the hot loop untouched.
  const uint8_t *p = dst;
  const uint8_t *pend = dst + n;
  const uint8_t *tok = p;
  for (;;) {
    tok = p;
    uint8_t t = *p++;
    uint64_t lit = t >> 4;
    if (lit == 15) {
      uint8_t b;
      do { b = *p++; lit += b; } while (b == 255);
    }
    p += lit;
    if (p >= pend) {          // literals reach end-of-stream: final sequence
      *tail_off = uint64_t(tok - dst);
      *tail_lit = lit;
      return n;
    }
    p += 2;                   // match offset
    if ((t & 0x0F) == 15) {
      uint8_t b;
      do { b = *p++; } while (b == 255);
    }
  }
}

// Assemble an LZ4 block from externally discovered match records.
//
// This is the host half of the TPU LZ4 path (ops/lz4_tpu.py): the device
// finds candidate matches (pos, offset, estimated length) with a sorted
// fingerprint scan; this function runs the greedy parse over those records
// and serializes standard LZ4 block format.  It re-verifies every record
// against the source bytes and extends matches exactly (forward and
// backward), so output correctness never depends on the device results —
// only the compression ratio does.
//
// recs: nrec records sorted by position ascending; pos[i] is the byte
// position, dl[i] packs (offset << 16) | est_len (offset 1..65535).
// Returns compressed size, or 0 if dst too small / input empty.
uint64_t hdrf_lz4_emit(const uint8_t *src, uint64_t srclen, const int32_t *pos,
                       const uint32_t *dl, uint64_t nrec, uint8_t *dst,
                       uint64_t dstcap) {
  if (srclen == 0 || dstcap < hdrf_lz4_compress_bound(srclen)) return 0;
  const uint8_t *iend = src + srclen;
  const uint8_t *matchlimit = iend - LAST_LITERALS;
  const uint8_t *mflimit = srclen > MFLIMIT ? iend - MFLIMIT : src;
  const uint8_t *anchor = src;
  uint8_t *op = dst;

  // Lazy parse over the record stream.  The device's estimated lengths
  // systematically undershoot whenever a nearer duplicate interrupts a
  // same-delta run (a long periodic match overlaid with RLE), so records are
  // re-verified and exactly extended here, and at each step ALL records
  // usable at the cursor (start within LAZY bytes) compete on true extended
  // end — the record whose match reaches furthest wins.  That recovers the
  // long structural match when the device's nearest-occurrence rule favored
  // a short-range RLE reference.  (On full TeraGen-density data the TpuLz4
  // front end falls back to hdrf_lz4_compress before reaching this parse —
  // the probe machinery below earns its keep on structured-but-not-flooded
  // containers and on the sparse-record grey zone.)
  //
  // Probe-offset trial: the device records carry STRUCTURAL matches (the
  // degenerate-gram filter keeps RLE interiors out of the sort), so the
  // gap between records is scanned against a tiny probe set — the last
  // emitted offset (periodic data like TeraGen re-enters its row-period
  // match after each random key) plus constants 1/2/4 (byte/word RLE,
  // which LZ4 encodes as overlapping matches).  One 4-byte compare per
  // (position, probe), resumed monotonically (probe_scan) so the whole
  // input costs O(n * nprobes).  A hit competes with the records like
  // any candidate.
  // Candidate windows: from a RECORD base, a narrow window (3) — on
  // short-match-dense text a wide window prefers later-longer matches and
  // loses the dense chain (measured 1.12x -> 1.44x of native).  From a
  // PROBE-HIT base (short RLE reference on periodic data), a wide window
  // (12) — the structural record starting a few bytes later must compete,
  // or TeraGen-style rows fragment into per-run RLE matches (measured
  // 4.57x vs 5.35x).
  constexpr uint64_t LAZY_REC = 3;
  constexpr uint64_t LAZY_PROBE = 12;
  uint64_t r = 0;
  uint32_t rep = 0, rep2 = 0;       // last two DISTINCT emitted offsets:
  // periodic row data alternates offsets (row-period rowid match vs the
  // period-minus-block filler match), and each re-entry needs its own
  // Per-probe monotone scanners (vectorized probe trial): each slot walks
  // the input once with the word-at-a-time scan_eq4, caching its next hit.
  // Slots 2-4 (constant offsets 1/2/4) never rescan ground; slots 0/1
  // restart from the anchor when their offset changes — semantically
  // identical to the global rescan-on-new-offset rule they replace (a
  // re-scan with unchanged constant offsets can find nothing new).
  struct PSlot { uint32_t off; const uint8_t *scanned; const uint8_t *hit; };
  PSlot slots[5] = {{0, src, nullptr}, {0, src, nullptr},
                    {1, src, nullptr}, {2, src, nullptr}, {4, src, nullptr}};
  while (anchor < mflimit) {
    uint64_t acur = uint64_t(anchor - src);
    // Drop records whose verified span (+ slack for under-estimation) is
    // wholly behind the cursor; keeps the candidate window short.
    while (r < nrec && uint64_t(pos[r]) + (dl[r] & 0xFFFF) + 64 < acur) r++;
    const uint8_t *rbase =
        r < nrec ? (src + pos[r] > anchor ? src + pos[r] : anchor) : mflimit;
    // Probe scan of [anchor, min(rbase+LAZY, mflimit)).
    const uint8_t *rep_hit = nullptr;
    uint32_t hit_off = 0;
    {
      if (slots[0].off != rep) {
        slots[0].off = rep; slots[0].scanned = anchor; slots[0].hit = nullptr;
      }
      if (slots[1].off != rep2) {
        slots[1].off = rep2; slots[1].scanned = anchor; slots[1].hit = nullptr;
      }
      const uint8_t *lim = rbase + LAZY_PROBE < mflimit
                               ? rbase + LAZY_PROBE : mflimit;
      for (int k = 0; k < 5; k++) {
        uint32_t off = slots[k].off;
        if (off == 0) continue;
        if (k >= 2 && (off == rep || off == rep2)) continue;  // dedup
        if (k == 1 && off == rep) continue;
        const uint8_t *start = anchor;
        if (src + off > start) start = src + off;
        if (slots[k].hit != nullptr && slots[k].hit < start) {
          // cached hit consumed/passed: unscanned ground resumes at start
          slots[k].hit = nullptr;
          slots[k].scanned = start;
        } else if (slots[k].hit == nullptr && slots[k].scanned < start) {
          slots[k].scanned = start;
        }
        if (slots[k].hit == nullptr && slots[k].scanned < lim) {
          const uint8_t *h = scan_eq4(slots[k].scanned, lim, off);
          slots[k].scanned = h ? h : lim;
          slots[k].hit = h;
        }
        if (slots[k].hit != nullptr && slots[k].hit < lim &&
            (rep_hit == nullptr || slots[k].hit < rep_hit)) {
          rep_hit = slots[k].hit;   // strict < : position ties keep the
          hit_off = off;            // lowest-k probe, as the byte loop did
        }
      }
    }
    const uint8_t *base = rep_hit && rep_hit < rbase ? rep_hit : rbase;
    const uint64_t LAZY = (rep_hit && rep_hit < rbase) ? LAZY_PROBE
                                                       : LAZY_REC;
    if (base >= mflimit) break;
    const uint8_t *bip = nullptr, *bref = nullptr, *bend = nullptr;
    for (uint64_t q = r; q < nrec && src + pos[q] <= base + LAZY; q++) {
      uint32_t off = dl[q] >> 16;
      if (off == 0) continue;
      const uint8_t *ip = src + pos[q];
      if (ip < anchor) ip = anchor;
      if (ip >= mflimit || uint64_t(ip - src) < off) continue;
      const uint8_t *ref = ip - off;
      if (read32(ip) != read32(ref)) continue;  // pad artifact / stale record
      const uint8_t *mip = ip + MIN_MATCH;
      const uint8_t *mref = ref + MIN_MATCH;
      while (mip < matchlimit && *mip == *mref) { mip++; mref++; }
      while (ip > anchor && ref > src && ip[-1] == ref[-1]) { ip--; ref--; }
      if (bend == nullptr || mip > bend || (mip == bend && ip < bip)) {
        bip = ip; bref = ref; bend = mip;
      }
    }
    if (rep_hit && rep_hit <= base + LAZY && rep_hit < mflimit) {
      const uint8_t *ip = rep_hit;
      const uint8_t *ref = ip - hit_off;
      const uint8_t *mip = ip + MIN_MATCH;
      const uint8_t *mref = ref + MIN_MATCH;
      while (mip < matchlimit && *mip == *mref) { mip++; mref++; }
      while (ip > anchor && ref > src && ip[-1] == ref[-1]) { ip--; ref--; }
      if (bend == nullptr || mip > bend || (mip == bend && ip < bip)) {
        bip = ip; bref = ref; bend = mip;
      }
    }
    if (bend == nullptr) { if (r >= nrec) break; r++; continue; }

    uint64_t matchlen = uint64_t(bend - bip);
    uint64_t litlen = uint64_t(bip - anchor);
    uint32_t offset = uint32_t(bip - bref);
    if (offset != rep) { rep2 = rep; rep = offset; }
    uint8_t *token = op++;
    if (litlen >= 15) {
      *token = 0xF0;
      op = write_len_ext(op, litlen - 15);
    } else {
      *token = uint8_t(litlen << 4);
    }
    memcpy(op, anchor, litlen);
    op += litlen;
    *op++ = uint8_t(offset);
    *op++ = uint8_t(offset >> 8);
    uint64_t mlcode = matchlen - MIN_MATCH;
    if (mlcode >= 15) {
      *token |= 0x0F;
      op = write_len_ext(op, mlcode - 15);
    } else {
      *token |= uint8_t(mlcode);
    }
    anchor = bend;
  }

  // Final literals-only sequence.
  uint64_t litlen = uint64_t(iend - anchor);
  uint8_t *token = op++;
  if (litlen >= 15) {
    *token = 0xF0;
    op = write_len_ext(op, litlen - 15);
  } else {
    *token = uint8_t(litlen << 4);
  }
  memcpy(op, anchor, litlen);
  op += litlen;
  return uint64_t(op - dst);
}

// Returns decompressed size, or 0 on malformed input / overflow.
uint64_t hdrf_lz4_decompress(const uint8_t *src, uint64_t srclen, uint8_t *dst,
                             uint64_t dstcap) {
  const uint8_t *ip = src, *iend = src + srclen;
  uint8_t *op = dst, *oend = dst + dstcap;
  while (ip < iend) {
    uint8_t token = *ip++;
    // Literals.
    uint64_t litlen = token >> 4;
    if (litlen == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return 0;
        b = *ip++;
        litlen += b;
      } while (b == 255);
    }
    if (uint64_t(iend - ip) < litlen || uint64_t(oend - op) < litlen) return 0;
    memcpy(op, ip, litlen);
    ip += litlen;
    op += litlen;
    if (ip == iend) break;  // last sequence has no match part
    // Match.
    if (iend - ip < 2) return 0;
    uint32_t offset = uint32_t(ip[0]) | (uint32_t(ip[1]) << 8);
    ip += 2;
    if (offset == 0 || offset > uint64_t(op - dst)) return 0;
    uint64_t matchlen = (token & 0x0F);
    if (matchlen == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return 0;
        b = *ip++;
        matchlen += b;
      } while (b == 255);
    }
    matchlen += MIN_MATCH;
    if (uint64_t(oend - op) < matchlen) return 0;
    const uint8_t *match = op - offset;
    // Byte-wise copy: offsets < matchlen intentionally replicate (RLE).
    for (uint64_t i = 0; i < matchlen; i++) op[i] = match[i];
    op += matchlen;
  }
  return uint64_t(op - dst);
}

// Decode the delta-encoded device record readback (ops/lz4_tpu.py packed
// layout) back into the (pos, (offset << 16) | len) records hdrf_lz4_emit
// consumes.  `row` starts at the A array (the 4-word header is consumed by
// the caller): A u32 x p3, B u32 x p3/4 (dpos low bytes, 4 per word), then
// two esc_slots-wide escape lanes (absolute entry-unit positions / lengths,
// record order).  All fields are in entry units (byte value / stride).
//
// Serial by necessity (each position is a prefix sum over deltas) but
// trivially so: one pass, ~5 loads per record.  Returns the number of
// records decoded — short of nv only when an escape lane overflowed on
// device (the caller then rescans in the full layout, or truncates if the
// device block is gone; truncation costs ratio, never correctness).
uint64_t hdrf_lz4_unpack_records(const uint32_t *row, uint64_t p3,
                                 uint64_t nv, uint64_t stride,
                                 uint64_t esc_slots, int32_t *pos_out,
                                 uint32_t *dl_out) {
  const uint32_t *A = row;
  const uint32_t *B = row + p3;
  const uint32_t *E1 = B + p3 / 4;
  const uint32_t *E2 = E1 + esc_slots;
  uint64_t e1 = 0, e2 = 0;
  uint64_t prev_u = 0;
  uint64_t i = 0;
  for (; i < nv; i++) {
    uint32_t a = A[i];
    uint32_t delta_u = a & 0x7FFF;
    uint32_t len9 = (a >> 15) & 0x1FF;
    uint32_t lo = (B[i >> 2] >> ((i & 3) * 8)) & 0xFF;
    uint32_t dp16 = ((a >> 24) << 8) | lo;
    uint64_t pos_u;
    if (dp16 == 0xFFFF) {
      if (e1 >= esc_slots) break;
      pos_u = E1[e1++];
    } else {
      pos_u = prev_u + dp16;
    }
    uint32_t len_u;
    if (len9 == 511) {
      if (e2 >= esc_slots) break;
      len_u = E2[e2++];
    } else {
      len_u = len9;
    }
    uint32_t mlen =
        len_u == 32766 ? 65535 : uint32_t(len_u * stride + MIN_MATCH);
    pos_out[i] = int32_t(pos_u * stride);
    dl_out[i] = (uint32_t(delta_u * stride) << 16) | mlen;
    prev_u = pos_u;
  }
  return i;
}

}  // extern "C"
