// Byte-range gather for the dedup commit path.
//
// Role-equivalent of the reference's storer-thread byte shuffling
// (DataDeduplicator.java:652-845 threadedStorer: per-chunk ByteBuffer
// slices copied into container buffers).  The Python half used to build a
// list of per-chunk memoryviews and b"".join them — ~1.2 s per 512 MiB of
// TeraGen-density chunks on the 1-vCPU DataNode host; this single memcpy
// loop replaces that.

#include <cstdint>
#include <cstring>

extern "C" {

// Concatenate n [starts[i], starts[i]+lens[i]) ranges of src into dst.
// Returns total bytes written.  Caller sizes dst = sum(lens).
uint64_t hdrf_gather_ranges(const uint8_t *src, uint64_t n,
                            const uint64_t *starts, const uint64_t *lens,
                            uint8_t *dst) {
  uint64_t at = 0;
  for (uint64_t i = 0; i < n; i++) {
    memcpy(dst + at, src + starts[i], lens[i]);
    at += lens[i];
  }
  return at;
}

}  // extern "C"
