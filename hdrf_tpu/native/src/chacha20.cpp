// ChaCha20-Poly1305 AEAD (RFC 8439), implemented from scratch.
//
// The native cipher behind the data-transfer encryption layer
// (hdrf_tpu/security.py) — the role the reference fills with SASL
// DIGEST-MD5 privacy / AES-CTR via JNI (datatransfer/sasl/,
// DataTransferSaslUtil).  Chosen over AES because it is fast in portable
// C++ (no AES-NI dependency) and the RFC ships authoritative test vectors
// (asserted in tests/test_security.py).

#include <cstdint>
#include <cstring>

namespace {

inline uint32_t rotl(uint32_t v, int n) { return (v << n) | (v >> (32 - n)); }

inline uint32_t load32(const uint8_t *p) {
  return uint32_t(p[0]) | uint32_t(p[1]) << 8 | uint32_t(p[2]) << 16 |
         uint32_t(p[3]) << 24;
}

inline void store32(uint8_t *p, uint32_t v) {
  p[0] = uint8_t(v); p[1] = uint8_t(v >> 8);
  p[2] = uint8_t(v >> 16); p[3] = uint8_t(v >> 24);
}

#define QR(a, b, c, d)                                        \
  a += b; d ^= a; d = rotl(d, 16);                            \
  c += d; b ^= c; b = rotl(b, 12);                            \
  a += b; d ^= a; d = rotl(d, 8);                             \
  c += d; b ^= c; b = rotl(b, 7);

void chacha20_block(const uint32_t state[16], uint8_t out[64]) {
  uint32_t x[16];
  memcpy(x, state, sizeof(x));
  for (int i = 0; i < 10; i++) {
    QR(x[0], x[4], x[8], x[12]);
    QR(x[1], x[5], x[9], x[13]);
    QR(x[2], x[6], x[10], x[14]);
    QR(x[3], x[7], x[11], x[15]);
    QR(x[0], x[5], x[10], x[15]);
    QR(x[1], x[6], x[11], x[12]);
    QR(x[2], x[7], x[8], x[13]);
    QR(x[3], x[4], x[9], x[14]);
  }
  for (int i = 0; i < 16; i++) store32(out + 4 * i, x[i] + state[i]);
}

void chacha20_init(uint32_t state[16], const uint8_t key[32],
                   const uint8_t nonce[12], uint32_t counter) {
  static const char sigma[17] = "expand 32-byte k";
  state[0] = load32(reinterpret_cast<const uint8_t *>(sigma));
  state[1] = load32(reinterpret_cast<const uint8_t *>(sigma) + 4);
  state[2] = load32(reinterpret_cast<const uint8_t *>(sigma) + 8);
  state[3] = load32(reinterpret_cast<const uint8_t *>(sigma) + 12);
  for (int i = 0; i < 8; i++) state[4 + i] = load32(key + 4 * i);
  state[12] = counter;
  state[13] = load32(nonce);
  state[14] = load32(nonce + 4);
  state[15] = load32(nonce + 8);
}

// Poly1305 (RFC 8439 §2.5), 26-bit limb implementation with a streaming
// state so the AEAD tag is computed incrementally over aad || pad || ct ||
// pad || lengths — no per-record allocation or extra ciphertext copy on the
// data hot path.
struct Poly1305 {
  uint32_t r0, r1, r2, r3, r4;
  uint32_t s1, s2, s3, s4;
  uint32_t h0 = 0, h1 = 0, h2 = 0, h3 = 0, h4 = 0;
  uint8_t key16[16];
  uint8_t carry[16];
  uint64_t carry_len = 0;

  explicit Poly1305(const uint8_t key[32]) {
    r0 = load32(key) & 0x3ffffff;
    r1 = (load32(key + 3) >> 2) & 0x3ffff03;
    r2 = (load32(key + 6) >> 4) & 0x3ffc0ff;
    r3 = (load32(key + 9) >> 6) & 0x3f03fff;
    r4 = (load32(key + 12) >> 8) & 0x00fffff;
    s1 = r1 * 5; s2 = r2 * 5; s3 = r3 * 5; s4 = r4 * 5;
    memcpy(key16, key + 16, 16);
  }

  void block(const uint8_t *b, uint32_t hibit) {
    h0 += load32(b) & 0x3ffffff;
    h1 += (load32(b + 3) >> 2) & 0x3ffffff;
    h2 += (load32(b + 6) >> 4) & 0x3ffffff;
    h3 += (load32(b + 9) >> 6) & 0x3ffffff;
    h4 += (load32(b + 12) >> 8) | hibit;

    uint64_t d0 = (uint64_t)h0 * r0 + (uint64_t)h1 * s4 + (uint64_t)h2 * s3 +
                  (uint64_t)h3 * s2 + (uint64_t)h4 * s1;
    uint64_t d1 = (uint64_t)h0 * r1 + (uint64_t)h1 * r0 + (uint64_t)h2 * s4 +
                  (uint64_t)h3 * s3 + (uint64_t)h4 * s2;
    uint64_t d2 = (uint64_t)h0 * r2 + (uint64_t)h1 * r1 + (uint64_t)h2 * r0 +
                  (uint64_t)h3 * s4 + (uint64_t)h4 * s3;
    uint64_t d3 = (uint64_t)h0 * r3 + (uint64_t)h1 * r2 + (uint64_t)h2 * r1 +
                  (uint64_t)h3 * r0 + (uint64_t)h4 * s4;
    uint64_t d4 = (uint64_t)h0 * r4 + (uint64_t)h1 * r3 + (uint64_t)h2 * r2 +
                  (uint64_t)h3 * r1 + (uint64_t)h4 * r0;

    uint64_t c = d0 >> 26; h0 = d0 & 0x3ffffff;
    d1 += c; c = d1 >> 26; h1 = d1 & 0x3ffffff;
    d2 += c; c = d2 >> 26; h2 = d2 & 0x3ffffff;
    d3 += c; c = d3 >> 26; h3 = d3 & 0x3ffffff;
    d4 += c; c = d4 >> 26; h4 = d4 & 0x3ffffff;
    h0 += uint32_t(c) * 5; c = h0 >> 26; h0 &= 0x3ffffff;
    h1 += uint32_t(c);
  }

  void update(const uint8_t *msg, uint64_t len) {
    if (carry_len) {
      while (carry_len < 16 && len) {
        carry[carry_len++] = *msg++;
        len--;
      }
      if (carry_len < 16) return;
      block(carry, 1 << 24);
      carry_len = 0;
    }
    while (len >= 16) {
      block(msg, 1 << 24);
      msg += 16;
      len -= 16;
    }
    if (len) {
      memcpy(carry, msg, len);
      carry_len = len;
    }
  }

  void final(uint8_t tag[16]);
};

void Poly1305::final(uint8_t tag[16]) {
  if (carry_len) {
    uint8_t b[16] = {0};
    memcpy(b, carry, carry_len);
    b[carry_len] = 1;
    block(b, 0);
  }
  // full carry + compare to p
  uint32_t c = h1 >> 26; h1 &= 0x3ffffff;
  h2 += c; c = h2 >> 26; h2 &= 0x3ffffff;
  h3 += c; c = h3 >> 26; h3 &= 0x3ffffff;
  h4 += c; c = h4 >> 26; h4 &= 0x3ffffff;
  h0 += c * 5; c = h0 >> 26; h0 &= 0x3ffffff;
  h1 += c;

  uint32_t g0 = h0 + 5; c = g0 >> 26; g0 &= 0x3ffffff;
  uint32_t g1 = h1 + c; c = g1 >> 26; g1 &= 0x3ffffff;
  uint32_t g2 = h2 + c; c = g2 >> 26; g2 &= 0x3ffffff;
  uint32_t g3 = h3 + c; c = g3 >> 26; g3 &= 0x3ffffff;
  uint32_t g4 = h4 + c - (1 << 26);
  uint32_t mask = (g4 >> 31) - 1;  // all-ones if h >= p
  h0 = (h0 & ~mask) | (g0 & mask);
  h1 = (h1 & ~mask) | (g1 & mask);
  h2 = (h2 & ~mask) | (g2 & mask);
  h3 = (h3 & ~mask) | (g3 & mask);
  h4 = (h4 & ~mask) | (g4 & mask);

  uint64_t f0 = ((h0) | (h1 << 26)) + (uint64_t)load32(key16);
  uint64_t f1 = ((h1 >> 6) | (h2 << 20)) + (uint64_t)load32(key16 + 4);
  uint64_t f2 = ((h2 >> 12) | (h3 << 14)) + (uint64_t)load32(key16 + 8);
  uint64_t f3 = ((h3 >> 18) | (h4 << 8)) + (uint64_t)load32(key16 + 12);
  store32(tag, uint32_t(f0)); f1 += f0 >> 32;
  store32(tag + 4, uint32_t(f1)); f2 += f1 >> 32;
  store32(tag + 8, uint32_t(f2)); f3 += f2 >> 32;
  store32(tag + 12, uint32_t(f3));
}

void poly1305_aead_tag(const uint8_t key[32], const uint8_t nonce[12],
                       const uint8_t *aad, uint64_t aad_len,
                       const uint8_t *ct, uint64_t ct_len, uint8_t tag[16]) {
  // one-time poly key = first 32 bytes of chacha block 0
  uint32_t state[16];
  uint8_t block0[64];
  chacha20_init(state, key, nonce, 0);
  chacha20_block(state, block0);
  // MAC input: aad || pad16 || ct || pad16 || le64(aad_len) || le64(ct_len)
  static const uint8_t zeros[16] = {0};
  uint8_t lens[16];
  for (int i = 0; i < 8; i++) lens[i] = uint8_t(aad_len >> (8 * i));
  for (int i = 0; i < 8; i++) lens[8 + i] = uint8_t(ct_len >> (8 * i));
  Poly1305 p(block0);
  p.update(aad, aad_len);
  p.update(zeros, (16 - (aad_len % 16)) % 16);
  p.update(ct, ct_len);
  p.update(zeros, (16 - (ct_len % 16)) % 16);
  p.update(lens, 16);
  p.final(tag);
}

}  // namespace

extern "C" {

// Raw keystream XOR (counter starts at 1 for AEAD payloads per RFC 8439).
void hdrf_chacha20_xor(const uint8_t *key, const uint8_t *nonce,
                       uint32_t counter, const uint8_t *in, uint64_t len,
                       uint8_t *out) {
  uint32_t state[16];
  chacha20_init(state, key, nonce, counter);
  uint8_t ks[64];
  uint64_t off = 0;
  while (off < len) {
    chacha20_block(state, ks);
    state[12]++;
    uint64_t n = len - off < 64 ? len - off : 64;
    for (uint64_t i = 0; i < n; i++) out[off + i] = in[off + i] ^ ks[i];
    off += n;
  }
}

// Seal: out = ciphertext(len) || tag(16).
void hdrf_aead_seal(const uint8_t *key, const uint8_t *nonce,
                    const uint8_t *aad, uint64_t aad_len, const uint8_t *pt,
                    uint64_t len, uint8_t *out) {
  hdrf_chacha20_xor(key, nonce, 1, pt, len, out);
  poly1305_aead_tag(key, nonce, aad, aad_len, out, len, out + len);
}

// Open: in = ciphertext(len) || tag(16); returns 1 on success (out = pt),
// 0 on authentication failure (out untouched).
int hdrf_aead_open(const uint8_t *key, const uint8_t *nonce,
                   const uint8_t *aad, uint64_t aad_len, const uint8_t *in,
                   uint64_t ct_len, uint8_t *out) {
  uint8_t tag[16];
  poly1305_aead_tag(key, nonce, aad, aad_len, in, ct_len, tag);
  uint8_t diff = 0;
  for (int i = 0; i < 16; i++) diff |= tag[i] ^ in[ct_len + i];
  if (diff) return 0;
  hdrf_chacha20_xor(key, nonce, 1, in, ct_len, out);
  return 1;
}

}  // extern "C"
