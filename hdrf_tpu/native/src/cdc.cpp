// Content-defined chunking (Gear rolling hash) — native CPU backend.
//
// Re-designs the reference's local-maximum CDC (DataDeduplicator.java:264-307,
// window 700 B, max chunk 1 MB) as Gear-hash CDC: h = (h << 1) + G[byte], with a
// cut-point candidate wherever (h & mask) == 0. Because `h << 1` discards a byte's
// contribution after 32 shifts, the hash at position p is a pure function of the
// trailing 32 bytes — making the candidate set position-independent and therefore
// (a) content-defined under insertions/deletions and (b) computable in parallel,
// which is what lets the same algorithm run as a TPU kernel (ops/cdc.py).
//
// The boundary *selection* (enforce min/max chunk) is inherently sequential but
// touches only the sparse candidate list (~len/2^mask_bits entries).

#include <cstdint>
#include <cstring>

namespace {

// Deterministic gear function shared with the JAX implementation (ops/gear.py):
// G[b] = fmix32(b * 0x9E3779B1) (murmur3 finalizer). Chosen to be *arithmetic*
// so the TPU side computes it with 6 elementwise VPU ops instead of a 256-entry
// gather (which scalarizes on TPU); the CPU side pre-tabulates it.
uint32_t fmix32(uint32_t z) {
  z ^= z >> 16;
  z *= 0x85EBCA6Bu;
  z ^= z >> 13;
  z *= 0xC2B2AE35u;
  z ^= z >> 16;
  return z;
}

struct GearTable {
  uint32_t g[256];
  GearTable() {
    for (int i = 0; i < 256; i++) g[i] = fmix32(uint32_t(i) * 0x9E3779B1u);
  }
};
const GearTable GT;

}  // namespace

extern "C" {

// Expose the gear table so Python/JAX builds bit-identical copies.
void hdrf_gear_table(uint32_t out[256]) { memcpy(out, GT.g, sizeof(GT.g)); }

// All candidate cut-points: p in [32, len] such that the gear hash of bytes
// [p-32, p) satisfies (h & mask) == 0. Cut-point p means "chunk may end before
// byte p". Returns the TOTAL number of candidates found (may exceed cap; only
// the first cap are written — callers detect overflow and retry with a larger
// buffer).
uint64_t hdrf_gear_candidates(const uint8_t *data, uint64_t len, uint32_t mask,
                              uint64_t *out_pos, uint64_t cap) {
  uint32_t h = 0;
  uint64_t n = 0;
  for (uint64_t i = 0; i < len; i++) {
    h = (h << 1) + GT.g[data[i]];
    if (i + 1 >= 32 && (h & mask) == 0) {
      if (n < cap) out_pos[n] = i + 1;
      n++;
    }
  }
  return n;
}

// Select chunk boundaries from a sorted candidate list, enforcing min/max chunk
// sizes. Shared by the CPU and TPU paths (the TPU kernel emits candidates; this
// resolves them). Rule per chunk starting at `start`:
//   lo = start + min_chunk, hi = min(start + max_chunk, len)
//   cut = first candidate in [lo, hi], else hi.
// Writes cut-points (exclusive chunk ends); the final cut is always `len`.
// Requires min_chunk >= 1 (guarantees progress). Returns number of cuts.
uint64_t hdrf_cdc_select(const uint64_t *cand, uint64_t ncand, uint64_t len,
                         uint64_t min_chunk, uint64_t max_chunk,
                         uint64_t *out_cuts, uint64_t cap) {
  uint64_t start = 0, n = 0, ci = 0;
  if (min_chunk == 0) min_chunk = 1;
  while (start < len && n < cap) {
    uint64_t lo = start + min_chunk;
    uint64_t hi = start + max_chunk;
    if (hi > len) hi = len;
    while (ci < ncand && cand[ci] < lo) ci++;
    uint64_t cut = (ci < ncand && cand[ci] <= hi) ? cand[ci] : hi;
    out_cuts[n++] = cut;
    start = cut;
  }
  return n;
}

// One-call sequential chunker (candidates + selection fused): the CPU baseline
// the >=4x TPU target is measured against, and the correctness oracle for the
// two-phase path. Bit-identical output to
// hdrf_gear_candidates(mask) + hdrf_cdc_select(min,max).
uint64_t hdrf_cdc_chunk(const uint8_t *data, uint64_t len, uint32_t mask,
                        uint64_t min_chunk, uint64_t max_chunk,
                        uint64_t *out_cuts, uint64_t cap) {
  uint64_t start = 0, n = 0;
  if (min_chunk == 0) min_chunk = 1;
  while (start < len && n < cap) {
    uint64_t lo = start + min_chunk;
    uint64_t hi = start + max_chunk;
    if (hi > len) hi = len;
    uint64_t cut = hi;
    if (lo <= hi) {
      // Gear hash at cut-point p covers bytes [p-32, p); warm up from lo-32.
      uint64_t warm = lo >= 32 ? lo - 32 : 0;
      uint32_t h = 0;
      for (uint64_t i = warm; i < lo && i < len; i++) h = (h << 1) + GT.g[data[i]];
      for (uint64_t p = lo; p <= hi; p++) {
        if (p >= 32 && (h & mask) == 0) { cut = p; break; }
        if (p < hi) h = (h << 1) + GT.g[data[p]];
      }
    }
    out_cuts[n++] = cut;
    start = cut;
  }
  return n;
}

}  // extern "C"
