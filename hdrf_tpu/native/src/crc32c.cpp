// CRC32C (Castagnoli) — packet checksum backend.
//
// The reference checksums every 512-byte chunk of the data-transfer stream with
// CRC32C (DataChecksum in hadoop-common, written from BlockReceiver.java:924-986).
// Slice-by-8 table-driven implementation.

#include <cstdint>
#include <cstring>

namespace {

struct Tables {
  uint32_t t[8][256];
  Tables() {
    const uint32_t poly = 0x82F63B78u;  // reflected Castagnoli
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c >> 1) ^ (poly & (0u - (c & 1)));
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++)
      for (int s = 1; s < 8; s++)
        t[s][i] = (t[s - 1][i] >> 8) ^ t[0][t[s - 1][i] & 0xFF];
  }
};
const Tables T;

}  // namespace

extern "C" {

uint32_t hdrf_crc32c(uint32_t crc, const uint8_t *data, uint64_t len) {
  crc = ~crc;
  while (len >= 8) {
    uint64_t v;
    memcpy(&v, data, 8);
    v ^= crc;  // little-endian assumption (x86-64 / TPU hosts)
    crc = T.t[7][v & 0xFF] ^ T.t[6][(v >> 8) & 0xFF] ^ T.t[5][(v >> 16) & 0xFF] ^
          T.t[4][(v >> 24) & 0xFF] ^ T.t[3][(v >> 32) & 0xFF] ^
          T.t[2][(v >> 40) & 0xFF] ^ T.t[1][(v >> 48) & 0xFF] ^
          T.t[0][(v >> 56) & 0xFF];
    data += 8;
    len -= 8;
  }
  while (len--) crc = (crc >> 8) ^ T.t[0][(crc ^ *data++) & 0xFF];
  return ~crc;
}

// Batch: CRC32C of each `chunk_size` slice of data (last may be short),
// writing one u32 per slice. Used for per-packet checksum arrays.
void hdrf_crc32c_chunks(const uint8_t *data, uint64_t len, uint64_t chunk_size,
                        uint32_t *out) {
  uint64_t n = 0;
  for (uint64_t off = 0; off < len; off += chunk_size)
    out[n++] = hdrf_crc32c(0, data + off,
                           (len - off < chunk_size) ? len - off : chunk_size);
}

}  // extern "C"
