// SHA-256 — native hashing backend.
//
// Role-equivalent of the reference's libnayuki-native-hashes.so (C/asm SHA-1/224
// reached over JNI from utilities.java:98-137). We standardize on SHA-256 for
// fingerprints (the reference used SHA-1/SHA-224; 256 matches the north-star spec)
// and expose a batch entry point so the ctypes boundary is crossed once per block,
// not once per chunk.

#include <cstdint>
#include <cstring>

namespace {

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

void compress(uint32_t state[8], const uint8_t *block) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = (uint32_t(block[4 * i]) << 24) | (uint32_t(block[4 * i + 1]) << 16) |
           (uint32_t(block[4 * i + 2]) << 8) | uint32_t(block[4 * i + 3]);
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
  uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + K[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  state[0] += a; state[1] += b; state[2] += c; state[3] += d;
  state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

void sha256_one(const uint8_t *data, uint64_t len, uint8_t out[32]) {
  uint32_t st[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  uint64_t i = 0;
  for (; i + 64 <= len; i += 64) compress(st, data + i);
  uint8_t tail[128];
  uint64_t rem = len - i;
  memcpy(tail, data + i, rem);
  tail[rem] = 0x80;
  uint64_t padlen = (rem < 56) ? 64 : 128;
  memset(tail + rem + 1, 0, padlen - rem - 1 - 8);
  uint64_t bits = len * 8;
  for (int j = 0; j < 8; j++) tail[padlen - 1 - j] = uint8_t(bits >> (8 * j));
  compress(st, tail);
  if (padlen == 128) compress(st, tail + 64);
  for (int j = 0; j < 8; j++) {
    out[4 * j] = uint8_t(st[j] >> 24);
    out[4 * j + 1] = uint8_t(st[j] >> 16);
    out[4 * j + 2] = uint8_t(st[j] >> 8);
    out[4 * j + 3] = uint8_t(st[j]);
  }
}

}  // namespace

extern "C" {

void hdrf_sha256(const uint8_t *data, uint64_t len, uint8_t out[32]) {
  sha256_one(data, len, out);
}

// Batch: hash n sub-ranges [offsets[i], offsets[i]+lengths[i]) of `data`,
// writing 32 bytes each to out + 32*i. Crosses the FFI boundary once per block —
// the reference pays a JNI crossing per chunk (utilities.java:98-103).
void hdrf_sha256_batch(const uint8_t *data, const uint64_t *offsets,
                       const uint64_t *lengths, uint64_t n, uint8_t *out) {
  for (uint64_t i = 0; i < n; i++)
    sha256_one(data + offsets[i], lengths[i], out + 32 * i);
}

}  // extern "C"
