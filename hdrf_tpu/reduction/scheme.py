"""ReductionScheme plugin registry — the abstraction the reference promises.

The reference README describes "an abstract class ReductionScheme ... selectable
in DataNode" (README.md:3) but ships no such class; scheme selection is a
hardcoded ``public static int compressor = 2`` switch (DataNode.java:438, modes
at :439-445).  This module is that promised abstraction, built for real:

==========  =======================  ====================================
ref mode    reference behavior        scheme name here
==========  =======================  ====================================
-1          direct file write         ``direct``
 0          Snappy stream             ``zstd`` (snappy-class speed, zstd format)
 1          dedup only                ``dedup``
 2          dedup + LZ4 containers    ``dedup_lz4``   (flagship, the default)
 3          Lzop stream               ``gzip`` (DEFLATE family)
 4          LZ4 stream                ``lz4``
 5          Gzip stream               ``gzip``
==========  =======================  ====================================

Schemes are selected **per file by explicit policy** (client passes the scheme
name at create; CreateOptions), not by the reference's fragile content sniffing
of MapReduce headers (BlockReceiver.java:800-820).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import TYPE_CHECKING

from hdrf_tpu.reduction import accounting
from hdrf_tpu.utils import codec as codecs

if TYPE_CHECKING:
    from hdrf_tpu.config import ReductionConfig
    from hdrf_tpu.index.chunk_index import ChunkIndex
    from hdrf_tpu.storage.container_store import ContainerStore


@dataclass
class ReductionContext:
    """Per-datanode resources a scheme may use."""

    config: "ReductionConfig"
    containers: "ContainerStore | None" = None
    index: "ChunkIndex | None" = None
    backend: str = "native"  # resolved execution backend for the hot ops
    # Co-located reduction worker client (reduction_worker.WorkerClient):
    # when set, schemes offload their hot ops to the worker process.
    worker: object | None = None
    # Device reconstructor (ops/reconstruct.DeviceReconstructor): when set,
    # reconstruction-heavy reads gather chunks from HBM-resident container
    # images instead of host memory.
    recon: object | None = None
    # Chunk-granular serving engine (server/read_plane.ReadPlane): when
    # set, dedup reconstruction serves chunk misses through its shared
    # decoded-chunk cache + read coalescer instead of per-read
    # read_chunks.  None keeps the direct container-store path (bench
    # micro-harnesses, tests).
    read_plane: object | None = None


class ReductionScheme(ABC):
    """A pluggable stage of the block write/read path.

    ``reduce`` maps a full logical block to the bytes stored in the replica
    data file (empty for dedup schemes, whose bytes land in chunk containers);
    ``reconstruct`` inverts it.  Both are whole-block on the write side —
    mirroring the reference, which buffers the block into ``bf1``
    (BlockReceiver.java:877-897) — while reads are chunk-granular where the
    stored form allows."""

    name: str = ""

    @abstractmethod
    def reduce(self, block_id: int, data: bytes, ctx: ReductionContext) -> bytes:
        ...

    @abstractmethod
    def reconstruct(self, block_id: int, stored: bytes, logical_len: int,
                    ctx: ReductionContext, offset: int = 0,
                    length: int = -1) -> bytes:
        ...

    def delete(self, block_id: int, ctx: ReductionContext) -> None:
        """Release out-of-band state (index rows, chunk refcounts)."""

    def describe(self) -> str:
        return self.name


_REGISTRY: dict[str, ReductionScheme] = {}


def register(scheme: ReductionScheme) -> ReductionScheme:
    _REGISTRY[scheme.name] = scheme
    return scheme


def get(name: str) -> ReductionScheme:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"unknown reduction scheme {name!r}; "
                       f"available: {sorted(_REGISTRY)}") from None


def names() -> list[str]:
    return sorted(_REGISTRY)


# ------------------------------------------------------------- basic schemes


class DirectScheme(ReductionScheme):
    """Identity — reference mode -1 (direct file write, DataNode.java:439)."""

    name = "direct"

    def reduce(self, block_id: int, data: bytes, ctx: ReductionContext) -> bytes:
        accounting.record_reduce(self.name, len(data), len(data))
        return data

    def reconstruct(self, block_id: int, stored: bytes, logical_len: int,
                    ctx: ReductionContext, offset: int = 0,
                    length: int = -1) -> bytes:
        end = logical_len if length < 0 else min(offset + length, logical_len)
        return stored[offset:end]


class CompressScheme(ReductionScheme):
    """Whole-block compression — reference's stream-codec modes (0/3/4/5),
    which pipe packets through a codec stream into ``chunkDir/<blkid>``
    (BlockReceiver.java:822-866) and stream-decompress on read
    (DataConstructor.java:102-220).  Codec impls live in utils/codec.py."""

    def __init__(self, codec: str):
        self.name = codec
        self._codec = codec

    def reduce(self, block_id: int, data: bytes, ctx: ReductionContext) -> bytes:
        from hdrf_tpu.ops import dispatch

        out = None
        if ctx.worker is not None:
            from hdrf_tpu.server.reduction_worker import WorkerError

            try:
                out = ctx.worker.compress(self._codec, data)
            except WorkerError:
                pass  # dead worker: host codec below
        if out is None:
            out = dispatch.block_compress(self._codec, data, ctx.backend)
        accounting.record_reduce(self.name, len(data), len(out))
        return out

    def reconstruct(self, block_id: int, stored: bytes, logical_len: int,
                    ctx: ReductionContext, offset: int = 0,
                    length: int = -1) -> bytes:
        full = codecs.decompress(self._codec, stored, logical_len)
        end = logical_len if length < 0 else min(offset + length, logical_len)
        return full[offset:end]


register(DirectScheme())
register(CompressScheme("lz4"))
register(CompressScheme("gzip"))
register(CompressScheme("zstd"))
# The reference's mode 0 (Snappy): python-snappy is an optional dependency;
# register only when importable (environment gating, not a hard requirement).
from hdrf_tpu.utils import codec as _codec  # noqa: E402

if _codec.available("snappy"):
    register(CompressScheme("snappy"))

# Dedup schemes register themselves on import (hdrf_tpu/reduction/dedup.py).
from hdrf_tpu.reduction import dedup as _dedup  # noqa: E402,F401
