"""Content-defined-chunking deduplication pipeline.

The write side re-expresses DataDeduplicator.java's per-block pipeline
(ctor :108-217): CDC chunking (:264-307) -> fingerprint (:312-332 via JNI SHA)
-> duplicate check (:338-367) -> container append with compress-on-rollover
(threadedStorer :652-845) -> index commit (:372-392).  The read side
re-expresses DataConstructor.java: hash-list fetch (:222-235), metadata batch
lookup + group-by-container (quickBuildMT :360-417), container read/decompress
and scatter (threadedConstructor :430-567).

Deliberate fixes over the reference:

- **Intra-block dedup actually works.** The reference keys a
  ``HashMap<byte[],...>`` on array identity, so duplicate chunks within one
  block are never detected (DataDeduplicator.java:340-358).  Here fingerprints
  are ``bytes`` keys; first occurrence wins.
- **Atomic commit.** Chunk bytes are fsync'd into containers *before* the
  single-WAL-record index commit, so a crash can orphan container bytes
  (reclaimed by compaction) but never index a chunk without bytes.  The
  reference's pipelined Redis SETs have no such ordering.
- **Chunk-granular reads.** ``reconstruct(offset, length)`` touches only the
  containers overlapping the requested range; the reference always
  materializes the full 128 MB block (BlockSender.java:612-623).
- **Refcounts + GC** (the reference's missing "Table #3",
  DataDeduplicator.java:61-62).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future

import numpy as np

from hdrf_tpu.ops import dispatch
from hdrf_tpu.reduction import accounting, scheme as scheme_mod
from hdrf_tpu.reduction.scheme import ReductionContext, ReductionScheme
from hdrf_tpu.server import read_plane as read_plane_mod
from hdrf_tpu.utils import fault_injection, metrics, profiler, tracing

_M = metrics.registry("dedup")

# Reads at least this large take the device reconstruction path when a
# DeviceReconstructor is attached (smaller reads: dispatch overhead wins).
DEVICE_RECON_MIN = 1 << 20


def _block_prep(data, cuts: np.ndarray, digests: np.ndarray):
    """Shared host prep: (memoryview, ordered hash list, first-occurrence
    byte ranges).  Vectorized: one tobytes() for all digests and the
    first-occurrence map via np.unique over a void view (the per-chunk
    dict-probe loop it replaces measured ~10% of the commit)."""
    mv = memoryview(data)
    starts = np.concatenate([[0], cuts[:-1]]).astype(np.int64)
    n = len(cuts)
    blob = np.ascontiguousarray(digests).tobytes()
    hashes = [blob[i << 5:(i + 1) << 5] for i in range(n)]
    if n:
        uniq_idx = np.sort(np.unique(digests.view("V32").reshape(-1),
                                     return_index=True)[1])
    else:
        uniq_idx = []
    first_range = {hashes[i]: (int(starts[i]), int(cuts[i] - starts[i]))
                   for i in uniq_idx}
    return mv, hashes, first_range


def _append_new(containers, data, first_range: dict, new_hashes: list,
                on_seal, sync: bool = True):
    """Container append of the new-chunk byte ranges as one native gather
    per container segment (threadedStorer's byte shuffling,
    DataDeduplicator.java:652-845, off the Python interpreter)."""
    if not new_hashes:
        return []
    rng = np.array([first_range[h] for h in new_hashes], dtype=np.uint64)
    arr = (data if isinstance(data, np.ndarray)
           else np.frombuffer(data, dtype=np.uint8))
    return containers.append_ranges(arr, rng[:, 0], rng[:, 1],
                                    on_seal=on_seal, sync=sync)


def dedup_commit(block_id: int, data: bytes, cuts: np.ndarray,
                 digests: np.ndarray, index, containers,
                 on_seal=None, probe=None) -> tuple[int, int]:
    """The host half of the write pipeline, given device/native reduction
    results: ordered hash list, first-occurrence ranges, index lookup,
    container append of unique bytes, single-record index commit
    (DataDeduplicator.java checkChunk :338-367 + storeChunksMT :511-532 +
    storeDB :372-392).  Shared by DedupScheme.reduce and the full-path
    benchmark so the timed path IS the product path.  ``probe`` (a set of
    fingerprints the mesh plane's device bucket table flagged as
    possibly-known) narrows the host index walk to probe POSITIVES: a
    stale-table false positive is resolved right here by the authoritative
    lookup, a false negative just re-appends bytes that ``commit_block``'s
    first-commit-wins rule turns into compactable orphans — never
    corruption.  Returns (chunk_count, new_unique_count, new_unique_bytes)."""
    with profiler.phase("dedup_lookup"):
        mv, hashes, first_range = _block_prep(data, cuts, digests)
        n = len(cuts)
        if index.get_block(block_id) is not None:
            # Supersede (append rewrote the block under a new gen stamp):
            # release the old entry's chunk refs before committing the new
            # one — CDC makes the rewrite dedup against its own old chunks,
            # so the released refs are mostly re-taken by the commit below.
            index.delete_block(block_id)
        if probe is None:
            known = index.lookup_chunks(list(first_range))
            new_hashes = [h for h, loc in known.items() if loc is None]
        else:
            cand = [h for h in first_range if h in probe]
            _M.incr("probe_skipped_lookups", len(first_range) - len(cand))
            known = index.lookup_chunks(cand)
            confirmed = sum(1 for loc in known.values() if loc is not None)
            _M.incr("probe_confirmed", confirmed)
            _M.incr("probe_false_positive", len(cand) - confirmed)
            new_hashes = [h for h in first_range if known.get(h) is None]
    with profiler.phase("container_io"):
        # ordering probe: tests park block K here and assert block K+1's
        # device dispatch is already enqueued (pipeline overlap contract)
        fault_injection.point("dedup.container_append", block_id=block_id)
        locs = _append_new(containers, data, first_range, new_hashes,
                           on_seal or index.seal_container)
    losers = index.commit_block(block_id, len(data), hashes,
                                dict(zip(new_hashes, locs)))
    if probe is not None and losers:
        # stale-table false negatives that raced a concurrent first commit:
        # their container bytes are orphans (reclaimed by compaction)
        _M.incr("probe_stale_appends", len(losers))
    _M.incr("chunks_total", n)
    _M.incr("chunks_new", len(new_hashes))
    new_bytes = sum(ln for _, _, ln in locs)
    _M.incr("bytes_new", new_bytes)
    accounting.record_dedup_block(n, len(new_hashes))
    return n, len(new_hashes), new_bytes


class CommitPipeline:
    """Asynchronous batched commit stage of the dedup write path.

    The reference runs container append + Redis SET in dedicated storer
    threads off the ingest thread (threadedStorer,
    DataDeduplicator.java:652-845) with NO durability barrier at all; here
    one worker thread keeps container layout deterministic while batching
    the durability cost: chunk bytes for up to ``batch`` queued blocks are
    appended unsynced, then ONE ``containers.sync_lanes()`` + ONE group
    WAL commit (``ChunkIndex.commit_blocks``) cover the whole batch, and
    only then do the blocks' futures resolve.  The index WAL record is
    always fsync'd; whether the chunk BYTES are fsync'd before it follows
    the store's ``fsync_containers`` policy (default off = HDFS block-data
    semantics: page-cache flush only, an OS crash loses the bytes and
    replication + the scanner recover the block).  A resolved future means
    "as durable as this deployment's policy makes a finalized replica",
    not an unconditional disk barrier."""

    def __init__(self, index, containers, batch: int = 4, on_seal=None):
        self._index = index
        self._containers = containers
        self._batch = batch
        self._on_seal = on_seal or index.seal_container
        # Seal compression runs on the store's seal worker, not this commit
        # thread: an unlucky 32 MiB rollover compress otherwise stalls every
        # group-committed block queued behind it.
        if hasattr(containers, "enable_async_seals"):
            containers.enable_async_seals()
        self._q: queue.Queue = queue.Queue()
        self._thread = threading.Thread(target=self._run,
                                        name="dedup-commit", daemon=True)
        self._thread.start()

    def submit(self, block_id: int, data, cuts: np.ndarray,
               digests: np.ndarray) -> Future:
        fut: Future = Future()
        self._q.put((block_id, data, cuts, digests, fut))
        profiler.counter_set("wal_queue_depth", self._q.qsize())
        return fut

    def close(self) -> None:
        self._q.put(None)
        self._thread.join()
        if hasattr(self._containers, "drain_seals"):
            self._containers.drain_seals()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            items = [item]
            while len(items) < self._batch:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._commit_batch(items)
                    return
                items.append(nxt)
            self._commit_batch(items)

    def _commit_batch(self, items: list) -> None:
        profiler.counter_set("wal_queue_depth", self._q.qsize())
        try:
            recs = []
            # chunks first seen earlier IN this batch: visible to later
            # blocks' dedup even though the index hasn't applied them yet
            pending_new: dict[bytes, tuple[int, int, int]] = {}
            for block_id, data, cuts, digests, _ in items:
                with profiler.phase("dedup_lookup"):
                    mv, hashes, first_range = _block_prep(data, cuts, digests)
                    if self._index.get_block(block_id) is not None:
                        self._index.delete_block(block_id)
                    probe = [h for h in first_range if h not in pending_new]
                    known = self._index.lookup_chunks(probe)
                new_hashes = [h for h in probe if known[h] is None]
                with profiler.phase("container_io"):
                    locs = _append_new(self._containers, data, first_range,
                                       new_hashes, self._on_seal, sync=False)
                new = dict(zip(new_hashes, locs))
                pending_new.update(new)
                recs.append((block_id, len(data), hashes, new))
                _M.incr("chunks_total", len(hashes))
                _M.incr("chunks_new", len(new_hashes))
                accounting.record_dedup_block(len(hashes), len(new_hashes))
            with profiler.phase("container_io"):
                self._containers.sync_lanes()  # bytes at least as durable as
                # the store's policy allows BEFORE the index references them
            self._index.commit_blocks(recs)
            for *_, fut in items:
                fut.set_result(None)
        except Exception as e:  # noqa: BLE001 — surface at the caller
            for *_, fut in items:
                if not fut.done():
                    fut.set_exception(e)


class DedupScheme(ReductionScheme):
    """CDC dedup; ``container_codec`` tells the DataNode how to build its
    ContainerStore (the rollover compression stage — reference mode 1 rolls
    containers uncompressed, mode 2 LZ4-compresses them)."""

    def __init__(self, name: str, container_codec: str):
        self.name = name
        self.container_codec = container_codec

    # --------------------------------------------------------------- write

    def reduce(self, block_id: int, data: bytes, ctx: ReductionContext) -> bytes:
        assert ctx.index is not None and ctx.containers is not None
        tr = tracing.current_context()
        with tracing.tracer("dedup").span("reduce", parent=tr) as sp:
            cuts = digests = None
            if ctx.worker is not None:
                from hdrf_tpu.server.reduction_worker import WorkerError

                try:
                    cuts, digests = ctx.worker.reduce(data, ctx.config.cdc)
                except WorkerError:
                    _M.incr("worker_fallbacks")  # dead worker: compute here
            if cuts is None:
                buf = np.frombuffer(data, dtype=np.uint8)
                cuts, digests = dispatch.chunk_and_fingerprint(
                    buf, ctx.config.cdc, ctx.backend)
            n, new, new_bytes = dedup_commit(block_id, data, cuts, digests,
                                             ctx.index, ctx.containers)
            sp.annotate("chunks", n)
            sp.annotate("unique_new", new)
            _M.incr("blocks_reduced")
            _M.incr("bytes_logical", len(data))
            accounting.record_reduce(self.name, len(data), new_bytes)
        return b""  # replica data file stays empty by design

    def reduce_with(self, block_id: int, data: bytes, cuts, digests,
                    ctx: ReductionContext, probe=None) -> bytes:
        """Commit with PRECOMPUTED device results — the streaming worker
        path: the DN already forwarded the packet stream to the worker and
        holds (cuts, digests) and, from the mesh plane, the on-device
        dedup-probe verdict set."""
        assert ctx.index is not None and ctx.containers is not None
        _, _, new_bytes = dedup_commit(block_id, data, cuts, digests,
                                       ctx.index, ctx.containers,
                                       probe=probe)
        _M.incr("blocks_reduced")
        _M.incr("bytes_logical", len(data))
        accounting.record_reduce(self.name, len(data), new_bytes)
        return b""

    # ---------------------------------------------------------------- read

    def reconstruct(self, block_id: int, stored: bytes, logical_len: int,
                    ctx: ReductionContext, offset: int = 0,
                    length: int = -1, plan=None) -> bytes:
        """Chunk-granular range read.  ``plan`` is a pre-resolved
        read_plane.ChunkPlan (the serving engine resolves once per request
        and threads it through); None resolves here — same index walk,
        same result."""
        assert ctx.index is not None and ctx.containers is not None
        if plan is None:
            with profiler.phase("index_lookup"):
                plan = read_plane_mod.resolve_chunk_plan(ctx.index, block_id,
                                                         offset, length)
        if plan.out_len == 0:
            return b""
        out = bytearray(plan.out_len)
        accounting.record_read_logical(self.name, plan.out_len)
        with accounting.read_scope(self.name):
            if ctx.recon is not None and plan.out_len >= DEVICE_RECON_MIN:
                # device read path (DataConstructor -> "Pallas gather" per
                # SURVEY §2.1): chunks gather from HBM-resident container
                # images; host pays one ordered copy pass
                with profiler.phase("container_decode"):
                    ctx.recon.gather(
                        plan.wanted,
                        lambda cid: ctx.containers.read_container(cid),
                        plan.spans, out)
                _M.incr("blocks_reconstructed_device")
                return bytes(out)
            if ctx.read_plane is not None:
                # shared decoded-chunk cache + coalesced container decodes
                # (the coalescer records its own container_decode spans)
                chunks = ctx.read_plane.fetch_chunks(plan)
                for chunk, (out_at, lo, n) in zip(chunks, plan.spans):
                    out[out_at:out_at + n] = chunk[lo:lo + n]
            else:
                with profiler.phase("container_decode"):
                    chunks = ctx.containers.read_chunks(plan.wanted)
                    for chunk, (out_at, lo, n) in zip(chunks, plan.spans):
                        out[out_at:out_at + n] = chunk[lo:lo + n]
        _M.incr("blocks_reconstructed")
        return bytes(out)

    def delete(self, block_id: int, ctx: ReductionContext) -> None:
        assert ctx.index is not None
        dead = ctx.index.delete_block(block_id)
        _M.incr("chunks_dead", len(dead))


scheme_mod.register(DedupScheme("dedup", container_codec="none"))
scheme_mod.register(DedupScheme("dedup_lz4", container_codec="lz4"))
scheme_mod.register(DedupScheme("dedup_zstd", container_codec="zstd"))
