"""Reduction-effectiveness accounting — the paper's headline metric, kept.

The reference computes its dedup/compression effectiveness offline from the
Redis tables (SURVEY.md §5); nothing in the running system can answer "how
much reduction am I getting?".  This module is the online answer: every
reduction observation point stamps into one ``reduction_accounting``
registry —

- per-scheme logical vs physical bytes (``logical_bytes__<scheme>`` /
  ``physical_bytes__<scheme>`` counters), fed by the schemes' reduce paths
  (reduction/scheme.py, reduction/dedup.py) and the co-located worker's
  compress ops (server/reduction_worker.py);
- per-block dedup hit/miss chunk counts (counters ``dedup_chunks_hit`` /
  ``dedup_chunks_miss`` + per-block histograms), fed by the same commit
  code dedup_commit / CommitPipeline already run
  (DataDeduplicator.java:338-367's checkChunk is the hit/miss point);
- refcount and container-utilization distributions, recomputed fresh from
  the chunk index's live tables (index/chunk_index.py:309-317's stats
  surface) by the DataNode's heartbeat assembly — state snapshots, not
  event streams, so they ride heartbeats as plain dicts.

The cluster dedup ratio is ``sum(logical_len) / sum(unique chunk bytes)``
over the chunk index — the standard effectiveness metric of the chunking
literature (arXiv:2505.21194 §V's dedup ratio) and *exactly* recomputable
from the index tables, which is what the acceptance check pins.

Everything here is host-side counter arithmetic on observation points that
already exist: zero device dispatches are added (the ledger event count
for a fixed workload is unchanged — utils/device_ledger.py is never
touched from this module).
"""

from __future__ import annotations

from hdrf_tpu.utils import metrics

_ACC = metrics.registry("reduction_accounting")


def record_reduce(scheme: str, logical_bytes: int,
                  physical_bytes: int) -> None:
    """Per-scheme logical vs physical byte accounting, stamped where a
    block's reduced form is produced."""
    _ACC.incr(f"logical_bytes__{scheme}", int(logical_bytes))
    _ACC.incr(f"physical_bytes__{scheme}", int(physical_bytes))


def record_dedup_block(chunks: int, new_chunks: int) -> None:
    """Per-block dedup hit/miss chunk accounting (a hit = a chunk whose
    fingerprint was already indexed; a miss appended new container
    bytes)."""
    hits = int(chunks) - int(new_chunks)
    _ACC.incr("dedup_chunks_hit", hits)
    _ACC.incr("dedup_chunks_miss", int(new_chunks))
    _ACC.observe("block_hit_chunks", hits)
    _ACC.observe("block_miss_chunks", int(new_chunks))


def record_worker_bytes(op: str, nbytes: int) -> None:
    """Reduction-worker stamp: bytes processed per worker op family."""
    _ACC.incr(f"worker_{op}_bytes", int(nbytes))


def record_stripe_tier(logical_bytes: int, physical_bytes: int) -> None:
    """EC cold-tier byte accounting (server/ec_tier.py's heartbeat stamp):
    logical = sealed-container bytes demoted to stripes, physical = stripe
    bytes on this DN's disk.  Gauges, not counters — the tier's CURRENT
    footprint, refreshed per heartbeat, so the cluster physical/logical ratio
    stays repr-exact as containers demote and repair."""
    _ACC.gauge("stripe_tier_logical_bytes", int(logical_bytes))
    _ACC.gauge("stripe_tier_physical_bytes", int(physical_bytes))


def stripe_ratio(logical_bytes: int, physical_bytes: int) -> float:
    """Stripe-tier physical/logical expansion: ~(k+m)/k (1.5 for RS(6,3))
    vs the replicated tier's replication factor; 0.0 for an empty tier."""
    return (physical_bytes / logical_bytes) if logical_bytes else 0.0


def snapshot() -> dict:
    """The registry snapshot (rides DN heartbeats; also on /prom and
    /metrics through the process-wide exposition)."""
    return _ACC.snapshot()


def dedup_ratio(logical_bytes: int, unique_chunk_bytes: int) -> float:
    """logical / unique-chunk bytes, 1.0 for an empty index — the exact
    ground-truth ratio the chunk index defines."""
    return (logical_bytes / unique_chunk_bytes) if unique_chunk_bytes else 1.0


def utilization_hist(live_bytes: dict, sizes: dict) -> dict:
    """Container-utilization decile histogram: live referenced bytes over
    bytes on disk, per container.  Sealed (compressed) containers can
    exceed 1.0 — that is the compression win showing up; dead weight
    (orphaned/dereferenced chunks) shows up as low deciles, the
    compaction-planning signal.  Buckets: 0..9 = [i/10, (i+1)/10), 10 =
    >= 1.0."""
    out: dict[int, int] = {}
    for cid, sz in sizes.items():
        u = (live_bytes.get(cid, 0) / sz) if sz else 0.0
        b = min(int(u * 10), 10)
        out[b] = out.get(b, 0) + 1
    return out
