"""Reduction-effectiveness accounting — the paper's headline metric, kept.

The reference computes its dedup/compression effectiveness offline from the
Redis tables (SURVEY.md §5); nothing in the running system can answer "how
much reduction am I getting?".  This module is the online answer: every
reduction observation point stamps into one ``reduction_accounting``
registry —

- per-scheme logical vs physical bytes (``logical_bytes__<scheme>`` /
  ``physical_bytes__<scheme>`` counters), fed by the schemes' reduce paths
  (reduction/scheme.py, reduction/dedup.py) and the co-located worker's
  compress ops (server/reduction_worker.py);
- per-block dedup hit/miss chunk counts (counters ``dedup_chunks_hit`` /
  ``dedup_chunks_miss`` + per-block histograms), fed by the same commit
  code dedup_commit / CommitPipeline already run
  (DataDeduplicator.java:338-367's checkChunk is the hit/miss point);
- read-amplification accounting (``read_logical_bytes__<scheme>`` vs
  ``read_physical_bytes__<scheme>`` vs ``read_stripe_bytes__<scheme>``):
  logical bytes served, physical container bytes actually decoded, and
  stripe bytes gathered for EC degraded reads — the serving-path mirror of
  the reduce-side ratio (DataConstructor.java:430-567 re-decompresses whole
  containers per read and never measures it);
- refcount and container-utilization distributions, recomputed fresh from
  the chunk index's live tables (index/chunk_index.py:309-317's stats
  surface) by the DataNode's heartbeat assembly — state snapshots, not
  event streams, so they ride heartbeats as plain dicts.

The cluster dedup ratio is ``sum(logical_len) / sum(unique chunk bytes)``
over the chunk index — the standard effectiveness metric of the chunking
literature (arXiv:2505.21194 §V's dedup ratio) and *exactly* recomputable
from the index tables, which is what the acceptance check pins.

Everything here is host-side counter arithmetic on observation points that
already exist: zero device dispatches are added (the ledger event count
for a fixed workload is unchanged — utils/device_ledger.py is never
touched from this module).
"""

from __future__ import annotations

import contextlib
import contextvars

from hdrf_tpu.utils import metrics

_ACC = metrics.registry("reduction_accounting")

# Ambient scheme tag for the READ side: the reconstruct entry point knows
# which scheme is serving, but the physical decode happens layers below in
# storage/container_store.py (which knows nothing about schemes) — the same
# contextvar trick the profiler uses for its ambient timeline.
_read_scheme: contextvars.ContextVar[str | None] = \
    contextvars.ContextVar("hdrf_read_scheme", default=None)


def record_reduce(scheme: str, logical_bytes: int,
                  physical_bytes: int) -> None:
    """Per-scheme logical vs physical byte accounting, stamped where a
    block's reduced form is produced."""
    _ACC.incr(f"logical_bytes__{scheme}", int(logical_bytes))
    _ACC.incr(f"physical_bytes__{scheme}", int(physical_bytes))


def record_dedup_block(chunks: int, new_chunks: int) -> None:
    """Per-block dedup hit/miss chunk accounting (a hit = a chunk whose
    fingerprint was already indexed; a miss appended new container
    bytes)."""
    hits = int(chunks) - int(new_chunks)
    _ACC.incr("dedup_chunks_hit", hits)
    _ACC.incr("dedup_chunks_miss", int(new_chunks))
    _ACC.observe("block_hit_chunks", hits)
    _ACC.observe("block_miss_chunks", int(new_chunks))


def record_worker_bytes(op: str, nbytes: int) -> None:
    """Reduction-worker stamp: bytes processed per worker op family."""
    _ACC.incr(f"worker_{op}_bytes", int(nbytes))


def record_stripe_tier(logical_bytes: int, physical_bytes: int) -> None:
    """EC cold-tier byte accounting (server/ec_tier.py's heartbeat stamp):
    logical = sealed-container bytes demoted to stripes, physical = stripe
    bytes on this DN's disk.  Gauges, not counters — the tier's CURRENT
    footprint, refreshed per heartbeat, so the cluster physical/logical ratio
    stays repr-exact as containers demote and repair."""
    _ACC.gauge("stripe_tier_logical_bytes", int(logical_bytes))
    _ACC.gauge("stripe_tier_physical_bytes", int(physical_bytes))


def stripe_ratio(logical_bytes: int, physical_bytes: int) -> float:
    """Stripe-tier physical/logical expansion: ~(k+m)/k (1.5 for RS(6,3))
    vs the replicated tier's replication factor; 0.0 for an empty tier."""
    return (physical_bytes / logical_bytes) if logical_bytes else 0.0


# ----------------------------------------------------- read amplification


@contextlib.contextmanager
def read_scope(scheme: str):
    """Tag the ambient read with its serving scheme so the container
    store's decode point (storage/container_store.py read_container) can
    attribute physical decoded bytes per scheme without knowing schemes
    exist."""
    tok = _read_scheme.set(scheme)
    try:
        yield
    finally:
        _read_scheme.reset(tok)


def record_read_logical(scheme: str, nbytes: int) -> None:
    """Logical bytes served to a reader, per scheme (the denominator of
    the read-amplification ratio)."""
    _ACC.incr(f"read_logical_bytes__{scheme}", int(nbytes))


def record_container_decode(nbytes: int) -> None:
    """Physical container bytes DECODED to serve reads (cache hits decode
    nothing — that is the compounding win ROADMAP item 1 chases).  Scheme
    attribution comes from the ambient :func:`read_scope`; decodes outside
    any read scope (compaction, EC repair) book under ``raw``."""
    s = _read_scheme.get() or "raw"
    _ACC.incr(f"read_physical_bytes__{s}", int(nbytes))


def record_stripe_gather(nbytes: int) -> None:
    """Stripe bytes gathered over the wire/disk for EC degraded reads —
    the third rung of the amplification ladder (logical < decoded <
    gathered when a read has to reassemble a demoted container)."""
    s = _read_scheme.get() or "raw"
    _ACC.incr(f"read_stripe_bytes__{s}", int(nbytes))


def read_amplification_report() -> dict:
    """Per-scheme read-amplification ratios recomputed from the cumulative
    counters: ``physical / logical`` (and ``stripe / logical``) — 0.0 for
    schemes that served nothing yet.  Refreshes matching gauges so the
    ratios ride /prom next to the raw byte counters."""
    snap = _ACC.snapshot()["counters"]
    out: dict[str, dict] = {}
    for key, v in snap.items():
        if key.startswith("read_logical_bytes__"):
            scheme = key[len("read_logical_bytes__"):]
            logical = int(v)
            physical = int(snap.get(f"read_physical_bytes__{scheme}", 0))
            stripe = int(snap.get(f"read_stripe_bytes__{scheme}", 0))
            amp = physical / logical if logical else 0.0
            out[scheme] = {"logical_bytes": logical,
                           "physical_bytes": physical,
                           "stripe_bytes": stripe,
                           "read_amplification": amp,
                           "stripe_amplification":
                               stripe / logical if logical else 0.0}
            _ACC.gauge(f"read_amplification__{scheme}", amp)
    return out


def snapshot() -> dict:
    """The registry snapshot (rides DN heartbeats; also on /prom and
    /metrics through the process-wide exposition)."""
    read_amplification_report()  # refresh the derived gauges first
    return _ACC.snapshot()


def dedup_ratio(logical_bytes: int, unique_chunk_bytes: int) -> float:
    """logical / unique-chunk bytes, 1.0 for an empty index — the exact
    ground-truth ratio the chunk index defines."""
    return (logical_bytes / unique_chunk_bytes) if unique_chunk_bytes else 1.0


# ----------------------------------------------------- adaptive chunk sizing


def record_scan_summary(slab_survivors: int, candidates: int) -> None:
    """Sequence-select scan telemetry from the fused-CDC header lanes
    (ops/cdc_pallas.py H_SURV/H_CANDS, read in ops/resident.py
    _start_sha_fused off the one table readback that already happens):
    per-slab survivor rows and the masked candidate population that
    survived the skip-ahead dead zone.  Feeds the ``cdc_adaptive`` bench
    contract block (bench.py) and the geometry sweep
    (``benchmarks cdc``)."""
    _ACC.incr("cdc_scan_slab_survivors", int(slab_survivors))
    _ACC.incr("cdc_scan_candidates", int(candidates))


def note_geometry(cdc) -> None:
    """Effective CDC geometry gauges, stamped at the reduction dispatch
    funnel (ops/dispatch.py chunk_and_fingerprint).  Under the adaptive
    controller the live CdcConfig mutates between blocks, so the gauges —
    not the static config — are what tell an operator (and the bench
    contract) which geometry cuts are being made with right now."""
    _ACC.gauge("cdc_mask_bits_effective", int(cdc.mask_bits))
    _ACC.gauge("cdc_min_chunk_effective", int(cdc.min_chunk))


def record_retune(key: str, old, new) -> None:
    """One applied controller retune step (a DataNode reconfigure of a
    ``cdc_*`` key, server/datanode.py); the counter is the e2e proof the
    adaptive loop actually moved the geometry."""
    _ACC.incr("cdc_retunes")
    _ACC.incr(f"cdc_retunes__{key}")


def record_retune_rollback() -> None:
    """One guard-triggered geometry revert (tools/slo_report.py guard
    called from the DN tick): the counter is the e2e proof the regression
    guard actually protects the workload, not just flags it."""
    _ACC.incr("retune_rollbacks")


def dedup_counters() -> tuple[int, int]:
    """Cumulative (hit, miss) dedup chunk counters — the controller's
    observation signal, produced by record_dedup_block at the commit
    point."""
    c = _ACC.snapshot()["counters"]
    return int(c.get("dedup_chunks_hit", 0)), int(c.get("dedup_chunks_miss", 0))


class AdaptiveChunkController:
    """Content-adaptive chunk-size controller (ISSUE 15 leg 3; the
    adaptive-average-chunk-size observation of arXiv:2505.21194 §V: dedup
    yield is corpus-dependent, and a fixed geometry leaves either ratio or
    index pressure on the table).

    The controller is deliberately host-trivial: it watches the cumulative
    dedup hit/miss counters this module already maintains (the chunk-hit
    ratio vs index pressure the issue names), and when a full observation
    window of chunks shows the corpus is dedup-poor it COARSENS the mask
    by one bit (bigger average chunks -> fewer index entries and less
    per-chunk overhead for data that was never going to dedup); when the
    corpus dedups well it walks back toward ``target_mask_bits`` one bit
    at a time.  Every decision is returned as an ORDERED list of
    ``(config_key, value)`` reconfigure steps whose intermediate states
    all keep ``min_chunk <= max_chunk`` — they are applied through the
    DataNode's existing live-reconfig path (server/datanode.py
    reconfigure), never by poking the config directly, so validation,
    metrics, and the audit trail all see them.

    Geometry derivation: for mask bits ``b`` the average chunk is ``2^b``
    (ops/dispatch.py gear_mask), and the emitted window is
    ``min = max(cdc_min_size, 2^(b-2))``, ``max = 2^(b+3)`` — at the
    default target (b=13, min_size=512) this reproduces the shipped
    2048/65536 defaults exactly, so enabling the controller is a no-op
    until evidence accumulates.  Safety: retunes only change where NEW
    cuts land; committed fingerprints are content-addressed and reads
    resolve through the chunk index's offsets, so data written under any
    older geometry stays bit-identical (ARCHITECTURE.md decision 15).
    Every emittable geometry is pinned against the XLA oracle by a
    property test (tests/test_adaptive_cdc.py)."""

    MASK_BITS_MIN = 8      # avg 256 B — floor of the emit range
    MASK_BITS_MAX = 16     # avg 64 KiB — ceiling of the emit range
    LOW_HIT = 0.05         # window hit ratio below which we coarsen
    HIGH_HIT = 0.35        # ratio above which we walk back toward target

    def __init__(self, target_mask_bits: int = 13, min_size: int = 512,
                 window_chunks: int = 512):
        self.target = int(min(max(target_mask_bits, self.MASK_BITS_MIN),
                              self.MASK_BITS_MAX))
        self.min_size = int(min_size)
        self.window_chunks = int(window_chunks)
        self._seen_hit = 0
        self._seen_miss = 0
        self._win_hit = 0
        self._win_miss = 0
        # Windows still held after a guard rollback (slo_report.guard in
        # the DN tick): a retune the guard just reverted must not be
        # re-proposed from the very next window's evidence, or the loop
        # flaps retune/rollback forever.
        self._hold_windows = 0

    def geometry(self, mask_bits: int) -> tuple[int, int]:
        """(min_chunk, max_chunk) for a mask-bits setting."""
        mb = int(mask_bits)
        return max(self.min_size, 1 << (mb - 2)), 1 << (mb + 3)

    def emit_range(self):
        """Every (mask_bits, min_chunk, max_chunk) the controller can ever
        request — the domain of the oracle property test."""
        return [(mb, *self.geometry(mb))
                for mb in range(self.MASK_BITS_MIN, self.MASK_BITS_MAX + 1)]

    def observe(self, hit: int, miss: int,
                current_mask_bits: int) -> list[tuple[str, int]]:
        """Consume the CUMULATIVE dedup counters; once a full window of
        chunk commits has accumulated, return the ordered reconfigure
        steps (possibly none).  Call from the DN heartbeat tick."""
        dh, dm = int(hit) - self._seen_hit, int(miss) - self._seen_miss
        self._seen_hit, self._seen_miss = int(hit), int(miss)
        if dh < 0 or dm < 0:      # counter reset (restart): restart window
            self._win_hit = self._win_miss = 0
            return []
        self._win_hit += dh
        self._win_miss += dm
        total = self._win_hit + self._win_miss
        if total < self.window_chunks:
            return []
        ratio = self._win_hit / total
        self._win_hit = self._win_miss = 0
        if self._hold_windows > 0:
            self._hold_windows -= 1
            return []
        cur = int(current_mask_bits)
        if ratio < self.LOW_HIT:
            new = min(cur + 1, self.MASK_BITS_MAX)
        elif ratio > self.HIGH_HIT and cur != self.target:
            new = cur + (1 if self.target > cur else -1)
        else:
            return []
        if new == cur:
            return []
        return self.steps(cur, new)

    def note_rollback(self, hold_windows: int = 2) -> None:
        """The regression guard reverted the last retune: hold the next
        ``hold_windows`` full observation windows before proposing any
        new geometry, so a workload the guard judged worse under the new
        cuts cannot re-trigger the same retune immediately."""
        self._hold_windows = max(self._hold_windows, int(hold_windows))

    def steps(self, old_mask_bits: int,
              new_mask_bits: int) -> list[tuple[str, int]]:
        """Ordered reconfigure steps old -> new geometry.  Growing applies
        ``max`` before ``min`` (old min <= old max <= new max, then
        new min <= new max); shrinking applies ``min`` first, symmetric —
        so ``min_chunk <= max_chunk`` holds at every intermediate state
        the reconfigure validator checks."""
        mn_new, mx_new = self.geometry(new_mask_bits)
        _, mx_old = self.geometry(old_mask_bits)
        if mx_new >= mx_old:
            steps = [("cdc_max_chunk", mx_new), ("cdc_min_chunk", mn_new)]
        else:
            steps = [("cdc_min_chunk", mn_new), ("cdc_max_chunk", mx_new)]
        steps.append(("cdc_mask_bits", int(new_mask_bits)))
        return steps


def utilization_hist(live_bytes: dict, sizes: dict) -> dict:
    """Container-utilization decile histogram: live referenced bytes over
    bytes on disk, per container.  Sealed (compressed) containers can
    exceed 1.0 — that is the compression win showing up; dead weight
    (orphaned/dereferenced chunks) shows up as low deciles, the
    compaction-planning signal.  Buckets: 0..9 = [i/10, (i+1)/10), 10 =
    >= 1.0."""
    out: dict[int, int] = {}
    for cid, sz in sizes.items():
        u = (live_bytes.get(cid, 0) / sz) if sz else 0.0
        b = min(int(u * 10), 10)
        out[b] = out.get(b, 0) + 1
    return out
