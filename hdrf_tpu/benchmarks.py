"""In-tree performance harnesses.

Analogs of the reference's test-tree benchmarks:

- ``nn``   — metadata-storm harness: concurrent wire clients hammer
             create/stat/getBlockLocations/listing against a started
             NameNode; ONE JSON line with rpc_p99_ms, lock_saturation
             and the per-method lock-share curve (what
             NNThroughputBenchmark.java:97 never measured — it calls
             handlers in-process, so lock contention and RPC service
             time are invisible by construction).
- ``dfs``  — DFS write/read MB/s through a MiniCluster per reduction scheme
             (BenchmarkThroughput.java).
- ``ec``   — RS encode/decode MB/s + striped write/read MB/s
             (ErasureCodeBenchmarkThroughput.java).
- ``reduction`` — the block-reduction pipeline (what bench.py at the repo
             root reports to the driver), selectable backend.
- ``churn`` — long-horizon delete/rewrite lifecycle over a MiniCluster:
             storage_ratio / garbage / cache / read-p95 curves over time
             (no reference analog; the trajectory axis ROADMAP item 1
             calls the honest production number).

Run: ``python -m hdrf_tpu.benchmarks <which> [options]``; each prints
one JSON object per metric.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def _rate(n: int, t0: float) -> float:
    return n / (time.perf_counter() - t0)


def _nn_observer_ab(args) -> None:
    """Paired observer A/B (ISSUE 20): the same metadata storm run twice
    per round — leg A against a lone active, leg B with ``--observers``
    observer NNs tailing it and the HA proxy routing reads observer-first
    (state-id protocol; one msync barrier per data op buys read-your-writes
    for the reads that follow).  Medians over ``--rounds`` rounds (the
    PERF_NOTES paired-pass discipline: the VM's write-burst throttling
    hits whichever leg draws it).  Prints ONE JSON line: per-leg read p99
    + the ACTIVE's lock share of the read methods (the PR 18 /contention
    decomposition — near-zero in leg B is the whole point), plus
    observer_reads / observer_share / msync_p99_ms / observer_lag_txids."""
    import dataclasses
    import tempfile
    import threading

    from hdrf_tpu.config import NameNodeConfig
    from hdrf_tpu.proto.rpc import HaRpcClient
    from hdrf_tpu.server.namenode import NameNode
    from hdrf_tpu.utils import metrics, retry

    read_methods = ("stat", "get_block_locations", "listing")

    def _counter(reg: str, key: str) -> int:
        return metrics.registry(reg).snapshot()["counters"].get(key, 0)

    def leg(observer: bool) -> dict:
        clients = max(1, args.clients)
        per = max(1, args.ops // clients)
        meta = max(0, args.meta_per_op)
        obs_reads0 = _counter("client.ha", "observer_reads")
        bounces0 = _counter("client.ha", "observer_bounces")
        with tempfile.TemporaryDirectory() as d:
            cfg = NameNodeConfig(
                meta_dir=d, replication=1, heartbeat_interval_s=30.0,
                dead_node_interval_s=600.0, tail_interval_s=0.02)
            nn = NameNode(cfg).start()
            obs = []
            try:
                nn.rpc_register_datanode("dn-bench", ["127.0.0.1", 1])
                if observer:
                    for _k in range(max(1, args.observers)):
                        ob = NameNode(dataclasses.replace(
                            cfg, role="observer", port=0)).start()
                        ob.rpc_register_datanode("dn-bench",
                                                 ["127.0.0.1", 1])
                        obs.append(ob)
                addrs = [nn.addr] + [o.addr for o in obs]
                read_ms = [[] for _ in range(clients)]
                msync_ms = [[] for _ in range(clients)]
                errors = [0] * clients
                calls = [0] * clients

                def storm(w: int) -> None:
                    ha = HaRpcClient(addrs, observer_reads=observer)
                    try:
                        for i in range(per):
                            p = f"/storm/c{w}/{i // args.files}/f{i}"
                            try:
                                ha.call("create", path=p, client=f"s{w}")
                                alloc = ha.call("add_block", path=p,
                                                client=f"s{w}")
                                ha.call("complete", path=p, client=f"s{w}",
                                        block_lengths={
                                            alloc["block_id"]: 1024})
                                calls[w] += 3
                                if observer:
                                    t = time.perf_counter()
                                    ha.msync(wait_s=1.0)
                                    msync_ms[w].append(
                                        (time.perf_counter() - t) * 1e3)
                                for j in range(meta):
                                    which = (i * meta + j) % 3
                                    t = time.perf_counter()
                                    if which == 0:
                                        ha.call("stat", path=p)
                                    elif which == 1:
                                        ha.call("get_block_locations",
                                                path=p)
                                    else:
                                        ha.call("listing",
                                                path=f"/storm/c{w}/"
                                                     f"{i // args.files}")
                                    read_ms[w].append(
                                        (time.perf_counter() - t) * 1e3)
                                    calls[w] += 1
                            except Exception:  # noqa: BLE001 — count on
                                errors[w] += 1
                    finally:
                        ha.close()

                t0 = time.perf_counter()
                ts = [threading.Thread(target=storm, args=(w,))
                      for w in range(clients)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                dt = time.perf_counter() - t0
                lock = nn.rpc_contention()["lock"]
                flat = [x for lat in read_ms for x in lat]
                msy = [x for lat in msync_ms for x in lat]
                lag_txids = max((nn._editlog.seq - o._editlog.seq
                                 for o in obs), default=0)
                obs_reads = _counter("client.ha",
                                     "observer_reads") - obs_reads0
                return {
                    "ops_per_s": round(sum(calls) / dt) if dt > 0 else 0,
                    "errors": sum(errors),
                    "read_p99_ms": round(float(
                        np.percentile(flat, 99)) if flat else 0.0, 3),
                    "active_read_lock_share": round(sum(
                        lock["by_method"].get(m, {}).get("hold_share", 0.0)
                        for m in read_methods), 4),
                    "observer_reads": obs_reads,
                    "observer_share": round(obs_reads / len(flat), 4)
                    if flat else 0.0,
                    "observer_bounces": _counter(
                        "client.ha", "observer_bounces") - bounces0,
                    "msync_p99_ms": round(float(
                        np.percentile(msy, 99)) if msy else 0.0, 3),
                    "observer_lag_txids": lag_txids,
                }
            finally:
                for o in obs:
                    o.stop()
                nn.stop()
                retry.reset_breakers()

    rounds = max(1, args.rounds)
    a_rounds = [leg(False) for _ in range(rounds)]
    b_rounds = [leg(True) for _ in range(rounds)]

    def med(rs: list[dict], key: str) -> float:
        return float(np.median([r[key] for r in rs]))

    a_p99, b_p99 = med(a_rounds, "read_p99_ms"), med(b_rounds, "read_p99_ms")
    print(json.dumps({
        "bench": "nn_observer_ab",
        "rounds": rounds,
        "clients": max(1, args.clients),
        "data_ops": max(1, args.ops // max(1, args.clients))
        * max(1, args.clients),
        "observers": max(1, args.observers),
        "a": {"read_p99_ms": round(a_p99, 3),
              "active_read_lock_share": round(
                  med(a_rounds, "active_read_lock_share"), 4),
              "ops_per_s": round(med(a_rounds, "ops_per_s"))},
        "b": {"read_p99_ms": round(b_p99, 3),
              "active_read_lock_share": round(
                  med(b_rounds, "active_read_lock_share"), 4),
              "ops_per_s": round(med(b_rounds, "ops_per_s"))},
        "read_p99_ratio": round(b_p99 / a_p99, 3) if a_p99 > 0 else 0.0,
        "observer_reads": round(med(b_rounds, "observer_reads")),
        "observer_share": round(med(b_rounds, "observer_share"), 4),
        "observer_bounces": round(med(b_rounds, "observer_bounces")),
        "msync_p99_ms": round(med(b_rounds, "msync_p99_ms"), 3),
        "observer_lag_txids": round(med(b_rounds, "observer_lag_txids")),
        "errors": sum(r["errors"] for r in a_rounds + b_rounds),
    }))


def _nn_kill_active(args) -> None:
    """Kill-active-mid-storm scenario (ISSUE 20): readers hammer a file
    through the HA proxy (observer-routed) while the active NN dies
    abruptly a third of the way in; a FailoverController promotes the
    standby while observers keep serving staleness-bounded reads.  Prints
    ONE JSON line: reads served, read errors, responses staler than the
    bound (must be 0 — bounced reads retry, they never lie), and the
    write-unavailability window (kill -> first post-promotion write)."""
    import threading

    from hdrf_tpu.server.failover import FailoverController
    from hdrf_tpu.testing.minicluster import MiniCluster
    from hdrf_tpu.utils import metrics

    def _counter(reg: str, key: str) -> int:
        return metrics.registry(reg).snapshot()["counters"].get(key, 0)

    payload = b"observer-kill-active" * 200
    dur = max(2.0, args.duration)
    readers = max(1, args.clients)
    obs_reads0 = _counter("client.ha", "observer_reads")
    bounces0 = _counter("client.ha", "observer_bounces")
    with MiniCluster(n_datanodes=1, replication=1, ha=True,
                     observers=max(1, args.observers)) as mc:
        with mc.client("seed") as c:
            c.write("/kill/f0", payload)
            c.msync(wait_s=2.0)
        fc = FailoverController(mc.nn_addrs(), probe_interval_s=0.2,
                                grace=2).start()
        stop = threading.Event()
        reads = [0] * readers
        read_errors = [0] * readers
        stale = [0] * readers

        def reader(w: int) -> None:
            with mc.client(f"reader-{w}") as c:
                while not stop.is_set():
                    try:
                        data = c.read("/kill/f0")
                    except Exception:  # noqa: BLE001 — the verdict counts
                        read_errors[w] += 1
                        time.sleep(0.05)
                        continue
                    reads[w] += 1
                    if data != payload:
                        stale[w] += 1

        ts = [threading.Thread(target=reader, args=(w,))
              for w in range(readers)]
        for t in ts:
            t.start()
        time.sleep(dur / 3)
        t_kill = time.perf_counter()
        mc.kill_namenode()
        # write probe: the moment a mutation lands again, promotion is done
        failover_s = None
        deadline = time.monotonic() + dur
        with mc.client("write-probe") as c:
            k = 0
            while time.monotonic() < deadline:
                try:
                    c.write(f"/kill/probe{k}", b"x")
                    failover_s = time.perf_counter() - t_kill
                    break
                except Exception:  # noqa: BLE001 — still failing over
                    k += 1
                    time.sleep(0.1)
        time.sleep(max(0.0, dur / 3))
        stop.set()
        for t in ts:
            t.join()
        fc.stop()
    print(json.dumps({
        "bench": "nn_kill_active",
        "duration_s": dur,
        "readers": readers,
        "reads": sum(reads),
        "read_errors": sum(read_errors),
        "stale_beyond_bound": sum(stale),
        "failover_s": round(failover_s, 3) if failover_s else None,
        "observer_reads": _counter("client.ha",
                                   "observer_reads") - obs_reads0,
        "observer_bounces": _counter("client.ha",
                                     "observer_bounces") - bounces0,
    }))


def bench_nn(args) -> None:
    """Metadata-storm harness (ISSUE 18; the NNThroughputBenchmark.java:97
    successor): ``--clients`` concurrent WIRE clients each run a data op
    (create + addBlock + complete — the edit-log group-commit load shape)
    followed by ``--meta-per-op`` read-plane calls (stat /
    getBlockLocations / listing, round-robin), against a started NameNode
    over real RPC connections so the per-method service-time
    decomposition, the lock books and the handler-pool gauges all
    populate.  Prints exactly ONE JSON line: throughput, rolling
    ``rpc_p99_ms``, ``lock_saturation``, the rolling lock-wait p99, the
    top lock-holding method and the per-method lock-share curve.

    ISSUE 20 modes: ``--observer-ab`` runs the paired observer A/B legs,
    ``--kill-active`` the kill-active-mid-storm failover scenario."""
    if getattr(args, "observer_ab", False):
        return _nn_observer_ab(args)
    if getattr(args, "kill_active", False):
        return _nn_kill_active(args)
    import tempfile
    import threading

    from hdrf_tpu.config import NameNodeConfig
    from hdrf_tpu.proto.rpc import RpcClient
    from hdrf_tpu.server.namenode import NameNode

    with tempfile.TemporaryDirectory() as d:
        nn = NameNode(NameNodeConfig(
            meta_dir=d, replication=1,
            heartbeat_interval_s=30.0, dead_node_interval_s=600.0)).start()
        try:
            nn.rpc_register_datanode("dn-bench", ["127.0.0.1", 1])
            clients = max(1, args.clients)
            per = max(1, args.ops // clients)
            meta = max(0, args.meta_per_op)
            errors = [0] * clients
            calls = [0] * clients

            def storm(w: int) -> None:
                with RpcClient(nn.addr) as c:
                    for i in range(per):
                        # rotate subdirs so listings stay <= --files wide
                        p = f"/storm/c{w}/{i // args.files}/f{i}"
                        try:
                            c.call("create", path=p, client=f"s{w}")
                            alloc = c.call("add_block", path=p,
                                           client=f"s{w}")
                            c.call("complete", path=p, client=f"s{w}",
                                   block_lengths={alloc["block_id"]: 1024})
                            calls[w] += 3
                            for j in range(meta):
                                which = (i * meta + j) % 3
                                if which == 0:
                                    c.call("stat", path=p)
                                elif which == 1:
                                    c.call("get_block_locations", path=p)
                                else:
                                    c.call("listing",
                                           path=f"/storm/c{w}/"
                                                f"{i // args.files}")
                                calls[w] += 1
                            if w == 0 and i % 50 == 0:
                                c.call("heartbeat", dn_id="dn-bench")
                                calls[w] += 1
                        except Exception:  # noqa: BLE001 — count, keep going
                            errors[w] += 1

            t0 = time.perf_counter()
            ts = [threading.Thread(target=storm, args=(w,))
                  for w in range(clients)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            dt = time.perf_counter() - t0
            cont = nn.rpc_contention()
            lock = cont["lock"]
            shares = sorted(((m, r["hold_share"])
                             for m, r in lock["by_method"].items()),
                            key=lambda kv: kv[1], reverse=True)
            print(json.dumps({
                "bench": "nn_metadata_storm",
                "clients": clients,
                "data_ops": per * clients,
                "meta_per_op": meta,
                "rpc_calls": sum(calls),
                "errors": sum(errors),
                "ops_per_s": round(sum(calls) / dt) if dt > 0 else 0,
                "rpc_p99_ms": round(cont["rpc_p99_ms"], 3),
                "lock_saturation": round(lock["saturation"], 4),
                "lock_wait_p99_us": round(
                    lock["wait_us"].get("p99", 0.0), 1),
                "top_method": shares[0][0] if shares else None,
                "lock_share": {m: round(s, 4) for m, s in shares[:8]},
                "attributed_frac": round(cont["attributed_frac"], 4),
            }))
        finally:
            nn.stop()


def _dfs_pipeline_ab(args) -> None:
    """Paired write-pipeline A/B: ``--streams`` concurrent client streams
    through one DN at pipeline_depth=1 (serial legacy) vs ``--depth``,
    alternating the two builds each round and taking the MEDIAN of the
    per-round ratios (the paired protocol PERF_NOTES.md's e2e verdicts
    require — the VM's write-burst throttling stalls whichever pass draws
    it).  Prints exactly ONE JSON line."""
    import statistics
    import threading

    from hdrf_tpu.testing.minicluster import MiniCluster

    rng = np.random.default_rng(42)
    n = args.mb << 20
    payloads = []
    for _ in range(args.streams):
        a = rng.integers(0, 256, size=n, dtype=np.uint8)
        a[: n // 2] = rng.integers(97, 123, size=n // 2, dtype=np.uint8)
        payloads.append(a.tobytes())

    def one_pass(depth: int) -> float:
        overrides = {"pipeline_depth": depth,
                     "pipeline_max_inflight": max(args.streams, 4),
                     "max_concurrent_writes": max(args.streams, 4)}
        with MiniCluster(n_datanodes=1, replication=1,
                         block_size=1 << 20, backend=args.backend,
                         reduction_overrides=overrides) as mc:
            with mc.client("ab-warm") as c:     # compile/page-in warmup
                c.write("/ab/warm", payloads[0][: 1 << 20], scheme="dedup")
            errs: list[BaseException] = []

            def wr(s: int) -> None:
                try:
                    with mc.client(f"ab{s}") as c:
                        c.write(f"/ab/{s}", payloads[s], scheme="dedup")
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    errs.append(e)

            ts = [threading.Thread(target=wr, args=(s,))
                  for s in range(args.streams)]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            dt = time.perf_counter() - t0
            if errs:
                raise errs[0]
            return args.streams * n / dt / 2**20

    r1, rn, ratios = [], [], []
    for _ in range(args.rounds):
        a = one_pass(1)
        b = one_pass(args.depth)
        r1.append(a)
        rn.append(b)
        ratios.append(b / a)
    print(json.dumps({
        "op": "dfs write pipeline A/B (concurrent streams, paired)",
        "backend": args.backend, "streams": args.streams,
        "mb_per_stream": args.mb, "rounds": args.rounds,
        "depth": args.depth,
        "depth1_MBps": round(statistics.median(r1), 1),
        "depthN_MBps": round(statistics.median(rn), 1),
        "speedup": round(statistics.median(ratios), 3),
    }))


def bench_dfs(args) -> None:
    from hdrf_tpu.testing.minicluster import MiniCluster

    if args.pipeline_ab:
        return _dfs_pipeline_ab(args)

    rng = np.random.default_rng(42)
    n = args.mb << 20
    payload = rng.integers(0, 256, size=n, dtype=np.uint8)
    payload[: n // 2] = rng.integers(97, 123, size=n // 2, dtype=np.uint8)
    payload = payload.tobytes()
    with MiniCluster(n_datanodes=args.datanodes, replication=args.replication,
                     block_size=8 << 20) as mc:
        from hdrf_tpu.utils import device_ledger

        with mc.client("bench") as c:
            for scheme in args.schemes.split(","):
                led0 = device_ledger.stamp()
                t0 = time.perf_counter()
                c.write(f"/bench/{scheme}", payload, scheme=scheme)
                w = n / (time.perf_counter() - t0) / 2**20
                t0 = time.perf_counter()
                got = c.read(f"/bench/{scheme}")
                r = n / (time.perf_counter() - t0) / 2**20
                assert got == payload
                led = device_ledger.delta(led0)
                print(json.dumps({"scheme": scheme,
                                  "write_MBps": round(w, 1),
                                  "read_MBps": round(r, 1),
                                  "ledger": led,
                                  "stalls": led.get("stall_total", 0)}))


def bench_ec_repair_ab(args) -> None:
    """Paired repair A/B (ISSUE 16): classic full-gather decode vs the
    coded partial-sum exchange, over the same container, erasure pattern,
    and holder layout.  BEFORE timing, the coded fold is pinned
    bit-identical to the full-gather oracle
    (storage/stripe_store.py ``reconstruct_container``) on EVERY erasure
    pattern up to ``m`` losses — the acceptance bar is correctness first,
    wire ratio second.  The wire ledger mirrors the live path's
    accounting (server/coded_exchange.py ``book_repair_wire``): full
    gather ships k whole stripes to the repairing owner, the coded chain
    ships one (|missing|, stripe_len) fold, holder-local contributions
    are free, and the contributions additionally ride the smaller-of LZ4
    negotiation.  Slope method for the timings; prints exactly ONE JSON
    line."""
    import itertools

    import jax

    from hdrf_tpu.ops import rs
    from hdrf_tpu.server import coded_exchange
    from hdrf_tpu.storage import stripe_store

    k, m, _cell = rs.parse_policy(args.policy)
    rng = np.random.default_rng(7)
    n = args.mb << 20
    # half-compressible corpus: random tiles interleaved with repeated
    # text, the shape raw-codec container stripes actually have (sealed
    # lz4 containers stripe to incompressible bytes and ship raw — the
    # negotiation's enc flags report which regime this run measured)
    tile = rng.integers(0, 256, size=max(n // 2, 1), dtype=np.uint8)
    text = np.frombuffer(
        (b"the quick brown fox jumps over the lazy dog. " * 8192)
        [: max(n - tile.size, 1)], dtype=np.uint8)
    payload = np.concatenate([tile, text])[:n].tobytes()
    stripes, manifest = stripe_store.encode_container(payload, k, m)
    stripe_len = int(manifest["stripe_len"])
    arrs = {i: np.frombuffer(s, dtype=np.uint8)
            for i, s in enumerate(stripes)}
    dns = max(int(args.dns), 2)
    holder_of = {i: i % dns for i in range(k + m)}  # round-robin layout

    def coded_fold(missing: list[int], shards: dict[int, np.ndarray]):
        """The owner's view of one coded repair: per-holder partial sums
        (one bit-matmul each), XOR fold, plus the remote wire bytes."""
        have = sorted(shards)[:k]
        rows = rs.repair_rows(k, m, tuple(have), tuple(missing))
        col = {s: j for j, s in enumerate(have)}
        parts, remote = [], 0
        for h in range(dns):
            mine = [s for s in have if holder_of[s] == h]
            if not mine:
                continue
            st = np.stack([shards[s] for s in mine])
            parts.append(rs.partial_sums(
                st, rows[:, [col[s] for s in mine]]))
            if h != 0:  # holder 0 is the repairing owner: local = free
                remote = len(missing) * stripe_len  # ONE chained fold
        return rs.xor_fold(parts), remote

    # ---- oracle pin: every erasure pattern up to m losses, small corpus
    small, sman = stripe_store.encode_container(payload[: k * 256], k, m)
    sarrs = {i: np.frombuffer(s, dtype=np.uint8)
             for i, s in enumerate(small)}
    patterns = [list(c) for e in range(1, m + 1)
                for c in itertools.combinations(range(k + m), e)]
    oracle_ok = True
    for missing in patterns:
        shards = {i: a for i, a in sarrs.items() if i not in missing}
        want = stripe_store.reconstruct_container(
            dict(shards), sman, want=missing)
        fold, _ = coded_fold(missing, shards)
        for i, w in enumerate(missing):
            if fold[i].tobytes() != want[w]:
                oracle_ok = False

    # ---- paired timing on the full corpus; default is the common
    # single-loss repair (full gather pays k stripes of wire per ONE
    # rebuilt — the ratio the coded path collapses to ~1)
    e = max(1, min(int(args.erasures), m))
    missing = list(range(e))  # data stripes lost: decode-heavy for A
    survivors = {i: arrs[i] for i in range(k + m) if i not in missing}
    rebuilt = len(missing) * stripe_len

    def run_full():
        return stripe_store.reconstruct_container(
            dict(survivors), manifest, want=missing)

    def run_coded():
        return coded_fold(missing, survivors)

    def slope_mbps(fn) -> float:
        fn()  # warm: jit compile + page in
        t0 = time.perf_counter()
        fn()
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(args.inner):
            fn()
        tk = time.perf_counter() - t0
        per = ((tk - t1) / (args.inner - 1)) if args.inner > 1 else t1
        return rebuilt / max(per, 1e-9) / 2**20

    full_mbps = slope_mbps(run_full)
    coded_mbps = slope_mbps(run_coded)

    # ---- wire ledger (the live path's accounting, stamped in-registry)
    fold, remote_wire = run_coded()
    packed = coded_exchange.pack_many(
        [fold[i].tobytes() for i in range(len(missing))])
    coded_wire_packed = sum(len(p) for p, _ in packed)
    full_wire = sum(len(survivors[i]) for i in sorted(survivors)[:k])
    coded_exchange.book_repair_wire(remote_wire, rebuilt)
    print(json.dumps({
        "op": f"ec repair A/B [{args.policy}, slope]",
        "mb": args.mb, "backend": jax.default_backend(),
        "k": k, "m": m, "dns": dns, "inner": args.inner,
        "erasures": len(missing),
        "patterns_pinned": len(patterns),
        "parity_oracle_ok": bool(oracle_ok),
        "full_gather_MBps": round(full_mbps, 1),
        "coded_repair_MBps": round(coded_mbps, 1),
        "speedup": (round(coded_mbps / full_mbps, 3)
                    if full_mbps > 0 else None),
        "repair_wire_ratio_full": round(full_wire / rebuilt, 3),
        "repair_wire_ratio_coded": round(remote_wire / rebuilt, 3),
        "repair_wire_ratio_coded_lz4": round(
            coded_wire_packed / rebuilt, 3),
        "wire_saved_frac": round(1 - remote_wire / full_wire, 4),
    }))


def bench_ec(args) -> None:
    """EC cold-tier harness: paired encode / intact-reassembly /
    degraded-decode slopes over the container striping path
    (storage/stripe_store.py on top of ops/rs.py), slope method — one
    timed call vs ``--inner`` back-to-back calls, (t_k - t_1)/(k-1)
    dividing out the fixed dispatch constant (PERF_NOTES.md round 4's
    discipline).  The pair that matters is intact vs degraded: intact
    reassembly is pure CRC+concat (all k data stripes present), degraded
    drops the first m stripes (all-data erasures, the worst case) and
    decodes through parity on the device — their ratio is the cold
    tier's read penalty.  Parity is pinned against the GF log/antilog
    oracle (rs.encode_ref) before timing.  Prints exactly ONE JSON
    line."""
    if getattr(args, "repair_ab", False):
        return bench_ec_repair_ab(args)
    import jax

    from hdrf_tpu.ops import rs
    from hdrf_tpu.storage import stripe_store

    k, m, _cell = rs.parse_policy(args.policy)
    rng = np.random.default_rng(7)
    n = args.mb << 20
    payload = rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
    stripes, manifest = stripe_store.encode_container(payload, k, m)

    # pin vs the numpy GF oracle before trusting any timing
    padded = np.zeros(k * manifest["stripe_len"], dtype=np.uint8)
    padded[:n] = np.frombuffer(payload, dtype=np.uint8)
    ref = rs.encode_ref(padded.reshape(k, -1), m)
    oracle_ok = all(bytes(ref[i]) == stripes[k + i] for i in range(m))

    intact = {i: stripes[i] for i in range(k)}
    degraded = {i: stripes[i] for i in range(m, k + m)}

    def slope_mbps(fn) -> float:
        fn()  # warm: jit compile + page in
        t0 = time.perf_counter()
        fn()
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(args.inner):
            fn()
        tk = time.perf_counter() - t0
        per = ((tk - t1) / (args.inner - 1)) if args.inner > 1 else t1
        return n / max(per, 1e-9) / 2**20

    enc = slope_mbps(lambda: stripe_store.encode_container(payload, k, m))
    rd_ok = slope_mbps(
        lambda: stripe_store.reconstruct_container(intact, manifest))
    rd_deg = slope_mbps(
        lambda: stripe_store.reconstruct_container(degraded, manifest))
    print(json.dumps({
        "op": f"ec cold tier [{args.policy}, slope]",
        "mb": args.mb, "backend": jax.default_backend(),
        "k": k, "m": m, "inner": args.inner,
        "parity_oracle_ok": bool(oracle_ok),
        "encode_MBps": round(enc, 1),
        "intact_read_MBps": round(rd_ok, 1),
        "degraded_read_MBps": round(rd_deg, 1),
        "degraded_penalty": (round(rd_ok / rd_deg, 3)
                             if rd_deg > 0 else None),
        # the tier's expansion: (k+m)*stripe_len over true length
        "storage_ratio": round(
            (k + m) * manifest["stripe_len"] / manifest["length"], 4),
    }))


def bench_reduction(args) -> None:
    from hdrf_tpu.config import CdcConfig
    from hdrf_tpu.ops import dispatch

    rng = np.random.default_rng(3)
    n = args.mb << 20
    data = rng.integers(0, 256, size=n, dtype=np.uint8)
    cdc = CdcConfig()
    backend = dispatch.resolve_backend(args.backend)
    dispatch.chunk_and_fingerprint(data[: 1 << 20], cdc, backend)  # warm
    from hdrf_tpu.utils import device_ledger

    led0 = device_ledger.stamp()
    t0 = time.perf_counter()
    cuts, digs = dispatch.chunk_and_fingerprint(data, cdc, backend)
    mbps = n / (time.perf_counter() - t0) / 2**20
    led = device_ledger.delta(led0)
    print(json.dumps({"op": f"reduction pipeline [{backend}]",
                      "MBps": round(mbps, 1), "chunks": int(cuts.size),
                      "ledger": led,
                      "stalls": led.get("stall_total", 0)}))


def bench_recon(args) -> None:
    """Read-side reconstruction MB/s: host path vs device gather path
    (DataConstructor.java:360-567 vs ops/reconstruct.py).  Builds a dedup
    store once, then reconstructs blocks repeatedly — the device path's
    HBM-resident container images make repeat reads gather-only."""
    import dataclasses
    import tempfile

    from hdrf_tpu.config import ReductionConfig
    from hdrf_tpu.index.chunk_index import ChunkIndex
    from hdrf_tpu.ops.reconstruct import DeviceReconstructor
    from hdrf_tpu.reduction import scheme as schemes
    from hdrf_tpu.reduction.scheme import ReductionContext
    from hdrf_tpu.storage.container_store import ContainerStore

    rng = np.random.default_rng(5)
    n = args.mb << 20
    blocks = {}
    with tempfile.TemporaryDirectory() as d:
        cfg = ReductionConfig()
        if args.chunk_kb:
            # bigger lanes (the verdict's 64 KiB-lane case): per-lane
            # dispatch overhead amortizes with lane size on both gather
            # formulations
            import math

            from hdrf_tpu.config import CdcConfig

            kb = args.chunk_kb
            cfg = dataclasses.replace(cfg, cdc=CdcConfig(
                mask_bits=int(math.log2(kb)) + 10,
                min_chunk=(kb << 10) // 4, max_chunk=(kb << 10) * 4))
        ctx = ReductionContext(
            config=cfg,
            containers=ContainerStore(d + "/containers", codec="lz4"),
            index=ChunkIndex(d + "/index"), backend="native")
        s = schemes.get("dedup_lz4")
        per = 8 << 20
        for bid in range(n // per):
            data = rng.integers(0, 256, size=per, dtype=np.uint8)
            data[: per // 3] = rng.integers(97, 123, size=per // 3,
                                            dtype=np.uint8)
            blocks[bid] = data.tobytes()
            s.reduce(bid, blocks[bid], ctx)
        for label, rctx in (
                ("host", ctx),
                ("device", dataclasses.replace(
                    ctx, recon=DeviceReconstructor()))):
            for bid, data in blocks.items():  # warm (stage images/compile)
                assert s.reconstruct(bid, b"", len(data), rctx) == data
            t0 = time.perf_counter()
            total = 0
            for _ in range(args.repeats):
                for bid, data in blocks.items():
                    out = s.reconstruct(bid, b"", len(data), rctx)
                    total += len(out)
            mbps = total / (time.perf_counter() - t0) / 2**20
            print(json.dumps({"op": f"reconstruction [{label}]",
                              "MBps": round(mbps, 1)}))

        # Device GATHER service rate: the kernel's own throughput once
        # images are HBM-resident, with a tiny dependent readback (the
        # same framing bench.py uses — through the dev tunnel every
        # reconstructed byte pays the ~25 MB/s D2H link, which measures
        # the WAN, not the gather; on PCIe-attached chips the D2H is
        # noise and THIS rate bounds the read path).
        import jax
        import jax.numpy as jnp

        if jax.default_backend() != "cpu":
            from hdrf_tpu.ops.reconstruct import _bucket_of

            recon = DeviceReconstructor()
            s2 = dataclasses.replace(ctx, recon=recon)
            for bid, data in blocks.items():   # stage images
                assert s.reconstruct(bid, b"", len(data), s2) == data
            # group every block's chunks like DeviceReconstructor.gather
            jobs = []
            for bid in blocks:
                entry = ctx.index.get_block(bid)
                locmap = ctx.index.lookup_chunks(list(set(entry.hashes)))
                groups: dict = {}
                for h in entry.hashes:
                    loc = locmap[h]
                    b = _bucket_of(-(-loc.length // 64) + 1)
                    groups.setdefault((loc.container_id, b),
                                      []).append(loc)
                for (cid, b), locs in groups.items():
                    L = -(-len(locs) // 128) * 128
                    ol = np.zeros((2, L), np.int32)
                    for j, loc in enumerate(locs):
                        ol[0, j], ol[1, j] = loc.offset, loc.length
                    img = recon._image(
                        cid, lambda c=cid: ctx.containers.read_container(c))
                    jobs.append((img, jax.device_put(ol), b,
                                 sum(loc.length for loc in locs)))
            from hdrf_tpu.ops.gather_pallas import gather_pad_messages

            buckets = tuple(b for _, _, b, _ in jobs)
            imgs = [j[0] for j in jobs]
            ols = [j[1] for j in jobs]

            INNER = 8

            @jax.jit
            def fused(imgs, ols):
                # ONE device program per pass (per-group dispatches would
                # measure the transport's per-dispatch cost, not the
                # gather), with INNER salted iterations inside so the
                # ~100 ms awaited-readback RTT amortizes (the slope
                # method, PERF_NOTES.md; the +i byte offset defeats CSE
                # while staying inside the images' zero headroom)
                tot = jnp.uint64(0)
                for i in range(INNER):
                    for img, ol, b in zip(imgs, ols, buckets):
                        o = gather_pad_messages(img, ol.at[0].add(i), b)
                        tot += jnp.sum(o[:, :1].astype(jnp.uint64))
                return tot

            def one_pass():
                return float(fused(imgs, ols))  # dependent readback

            one_pass()  # compile
            t0 = time.perf_counter()
            for _ in range(args.repeats):
                one_pass()
            dt = time.perf_counter() - t0
            gathered = args.repeats * INNER * sum(j[3] for j in jobs)
            print(json.dumps({"op": "reconstruction [device gather kernel]",
                              "MBps": round(gathered / dt / 2**20, 1)}))
        ctx.index.close()


def bench_sort(args) -> None:
    """Match-scan sort engine A/B: the Pallas fused bitonic network
    (ops/sort_pallas.py) vs the ``jax.lax.sort`` reference, slope method —
    k salted iterations inside ONE dispatch with a dependent readback, so
    (T(k) - T(1)) / (k - 1) divides out the ~100 ms per-dispatch transport
    constant (PERF_NOTES.md round 4).  On the CPU mesh only the XLA path
    runs (Mosaic needs a real chip); ``--interpret`` forces the kernel
    through the Pallas interpreter for correctness spot-checks, not
    timing."""
    import jax
    import jax.numpy as jnp

    from hdrf_tpu.ops import sort_pallas

    rng = np.random.default_rng(13)
    t, e = args.tiles, args.entries
    stride, pos_bits = 2, int(e - 1).bit_length()
    vals = jnp.asarray(rng.integers(0, 2**32, size=(t, e), dtype=np.uint32))
    half = e // 2
    idx = np.arange(e)
    posn = jnp.asarray(np.where(idx < half, 2 * idx,
                                2 * (idx - half) + 1)
                       .astype(np.uint32))[None].repeat(t, axis=0)

    impls = ["xla"]
    if sort_pallas.use_pallas() or args.interpret:
        impls.append("pallas")

    def measure(build):
        def timed(k):
            f = jax.jit(build(k))
            float(f(vals))  # compile + warm
            t0 = time.perf_counter()
            for _ in range(args.repeats):
                float(f(vals))  # dependent readback acks real completion
            return (time.perf_counter() - t0) / args.repeats
        t1, tk = timed(1), timed(args.inner)
        return (tk - t1) / (args.inner - 1)

    for impl in impls:
        interp = args.interpret and impl == "pallas"

        def build(k, impl=impl, interp=interp):
            def f(v):
                acc = jnp.uint32(0)
                for i in range(k):
                    # the salt defeats CSE between iterations
                    d = sort_pallas.match_deltas(v ^ jnp.uint32(i), posn,
                                                 stride, pos_bits,
                                                 impl=impl,
                                                 interpret=interp)
                    acc += d[0, 0] + jnp.sum(d[:, -1])
                return acc
            return f

        per = measure(build)
        print(json.dumps({
            "op": f"match_deltas [{impl}{'/interp' if interp else ''}]",
            "entries": t * e, "ms_per_scan": round(per * 1e3, 3),
            "MBps": round(t * e * stride / per / 2**20, 1)}))

    for impl in impls:
        interp = args.interpret and impl == "pallas"

        def build(k, impl=impl, interp=interp):
            def f(v):
                acc = jnp.uint32(0)
                for i in range(k):
                    _, sv = sort_pallas.sort_rows(v ^ jnp.uint32(i), v,
                                                  impl=impl,
                                                  interpret=interp)
                    acc += sv[0, 0] + jnp.sum(sv[:, -1])
                return acc
            return f

        per = measure(build)
        print(json.dumps({
            "op": f"sort_rows [{impl}{'/interp' if interp else ''}]",
            "entries": t * e, "ms_per_sort": round(per * 1e3, 3),
            "Mkeys_per_s": round(t * e / per / 1e6, 1)}))

    # Readback-size ledger: the packed record layout vs the full one at the
    # production L3 width (deterministic; no device needed).
    from hdrf_tpu.ops.lz4_tpu import _packed_len

    p3 = 1 << 17
    full, packed = 1 + 2 * p3, _packed_len(p3)
    print(json.dumps({"op": "record readback", "p3": p3,
                      "full_words": full, "packed_words": packed,
                      "reduction_pct": round(100 * (1 - packed / full), 1)}))


def bench_cdc(args) -> None:
    """Fused Pallas CDC front end, geometry-sweepable A/B (ISSUE 15): the
    skip-ahead + sequence-select kernel vs the PR 4 fused scan vs the XLA
    ``_prep`` pipeline stage (ops/resident.py), slope method — k salted
    iterations in ONE dispatch with a dependent readback divides out the
    ~100 ms transport constant (PERF_NOTES.md round 4).  ``--mask-bits`` /
    ``--min-size`` sweep the geometry; ``--no-skip-ahead`` pins the PR 4
    scan alone.  Prints exactly ONE JSON line carrying the paired A/B, the
    per-leg micro-profile (gear = scan-only kernel slope, scan = fused
    minus gear, image = be_word_image slope, pad = sha_pad_messages
    slope — the round-17 PERF_NOTES table from one command), the kernel's
    H_SURV/H_CANDS telemetry, and the per-block readback byte ledger.
    Cuts are pinned bit-identical to native.cdc_chunk for every variant
    BEFORE any timing.  Without a chip the kernels run in the Pallas
    interpreter — a correctness-grade timing, flagged in the line (the
    round-6 precedent)."""
    import jax
    import jax.numpy as jnp

    from hdrf_tpu import native
    from hdrf_tpu.config import CdcConfig
    from hdrf_tpu.ops import cdc_pallas, resident

    cdc = CdcConfig(mask_bits=args.mask_bits, min_chunk=args.min_size)
    r = resident.ResidentReducer(cdc, fused_mode="off")
    n = args.mb << 20
    rng = np.random.default_rng(17)
    a = rng.integers(0, 256, n, dtype=np.uint8)
    a[: n // 4] = rng.integers(97, 123, size=n // 4, dtype=np.uint8)

    mode = cdc_pallas.cdc_pallas_mode()
    interpret = args.interpret or mode != "mosaic"
    plans = {}
    if args.skip_ahead:
        plans["skip"] = cdc_pallas.plan_for(
            n, r.mask, cdc.mask_bits, cdc.min_chunk, cdc.max_chunk,
            r._b_small, r._b_big, skip_ahead=True)
    plans["walk"] = cdc_pallas.plan_for(
        n, r.mask, cdc.mask_bits, cdc.min_chunk, cdc.max_chunk,
        r._b_small, r._b_big, skip_ahead=False)
    n_pad = max(p.n_pad for p in plans.values())
    buf = np.zeros(n_pad, dtype=np.uint8)
    buf[:n] = a
    w2d = jax.device_put(buf.view(np.uint32).reshape(-1, 128))
    pad512 = n + (-n) % 512
    blk = jax.device_put(np.concatenate([a, np.zeros(pad512 - n,
                                                     np.uint8)]))
    cap_x = max(1, min(pad512 // 32,
                       max(1024, (n >> max(cdc.mask_bits - 1, 0)) + 1024)))

    # -- correctness pin BEFORE timing: every variant's cuts must equal
    # the native oracle (overflow => the variant reports it and equality
    # is vacuous: callers take the oracle path).
    want = native.cdc_chunk(a.tobytes(), r.mask, cdc.min_chunk,
                            cdc.max_chunk)
    surv = cands = 0
    overflowed = False
    for name, p in plans.items():
        _, table, _, _ = jax.jit(
            lambda w, p=p: cdc_pallas.fused_block(w, p, interpret))(w2d)
        tb = np.asarray(table)[0]
        if int(tb[cdc_pallas.H_OVERFLOW]):
            overflowed = True
            continue
        nc = int(tb[cdc_pallas.H_COUNT])
        got = tb[cdc_pallas.TABLE_HDR:cdc_pallas.TABLE_HDR + nc].astype(
            np.uint64)
        assert np.array_equal(got, np.asarray(want, np.uint64)), \
            f"{name} kernel cuts diverge from native.cdc_chunk"
        if name == "skip":
            surv = int(tb[cdc_pallas.H_SURV])
            cands = int(tb[cdc_pallas.H_CANDS])

    def measure(build, inp):
        def timed(k):
            f = jax.jit(build(k))
            int(f(inp))                        # compile + warm
            t0 = time.perf_counter()
            for _ in range(args.repeats):
                int(f(inp))
            return (time.perf_counter() - t0) / args.repeats
        t1, tk = timed(1), timed(args.inner)
        return (tk - t1) / (args.inner - 1)

    def build_fused(p):
        def build(k):
            def f(w):
                acc = jnp.int32(0)
                for i in range(k):
                    _, table, _, _ = cdc_pallas.fused_block(
                        w ^ jnp.uint32(i), p, interpret)  # salt kills CSE
                    acc += table[0, cdc_pallas.H_COUNT]
                return acc
            return f
        return build

    def build_scan_only(k):
        # gear leg: the scan-only kernel shares the gear-map + window-hash
        # core but does NO cut selection — fused minus this is the select
        # leg the sequence-based scan targets.
        R_s = plans["walk"].R
        T = w2d.shape[0] // R_s
        pos0 = jnp.zeros((1, 1), jnp.int32)
        m32 = jnp.full((1, 1), r.mask, jnp.uint32)

        def f(w):
            acc = jnp.int32(0)
            for i in range(k):
                nib = cdc_pallas._scan_call(T, R_s, n, interpret)(
                    pos0, m32, w ^ jnp.uint32(i))
                acc += jnp.sum(nib)
            return acc
        return f

    def build_image(k):
        def f(b):
            acc = jnp.uint32(0)
            for i in range(k):
                acc += jnp.max(resident.be_word_image(b ^ jnp.uint8(i)))
            return acc
        return f

    L_pad = 1024
    ol_np = np.zeros((2, L_pad), dtype=np.int32)
    ol_np[0] = (np.arange(L_pad) * cdc.min_chunk) % max(n // 2, 1)
    ol_np[1] = min(cdc.min_chunk, r._b_small * 64 - 9)
    ol_dev = jax.device_put(ol_np)

    def build_pad(k):
        def f(w):
            acc = jnp.uint32(0)
            for i in range(k):
                out, _ = resident.sha_pad_messages(
                    w.reshape(-1) ^ jnp.uint32(i), ol_dev, r._b_small)
                acc += jnp.max(out)
            return acc
        return f

    def build_xla(k):
        def f(b):
            acc = jnp.uint32(0)
            for i in range(k):
                words, cand = resident._prep_impl(b ^ jnp.uint8(i & 0xFF),
                                                  r.mask, cap_x,
                                                  r.pad_words)
                acc += jnp.max(words) + cand[0].astype(jnp.uint32)
            return acc
        return f

    fused_ms = {name: measure(build_fused(p), w2d) * 1e3
                for name, p in plans.items()}
    gear_ms = measure(build_scan_only, w2d) * 1e3
    image_ms = measure(build_image, blk) * 1e3
    pad_ms = measure(build_pad, w2d) * 1e3
    xla_ms = measure(build_xla, blk) * 1e3
    best = fused_ms.get("skip", fused_ms["walk"])
    plan = plans.get("skip", plans["walk"])
    print(json.dumps({
        "op": "cdc_prep [skip-ahead vs pr4 fused vs xla prep, slope A/B]",
        "mb": args.mb, "backend": jax.default_backend(),
        "interpret": interpret,
        "mask_bits": cdc.mask_bits, "min_size": cdc.min_chunk,
        "skip_ahead": bool(args.skip_ahead),
        "cuts_verified": not overflowed, "overflowed": overflowed,
        "fused_ms_per_block": round(best, 3),
        "fused_noskip_ms_per_block": round(fused_ms["walk"], 3),
        "skip_ahead_speedup": (round(fused_ms["walk"] / best, 3)
                               if "skip" in fused_ms and best > 0 else None),
        "xla_ms_per_block": round(xla_ms, 3),
        "speedup": round(xla_ms / best, 3) if best > 0 else None,
        # Per-leg micro-profile (the PERF_NOTES round-17 table): scan =
        # what cut selection costs on top of the shared gear/hash core.
        "micro_profile_ms": {"gear": round(max(gear_ms, 0.0), 3),
                             "scan": round(max(best - gear_ms, 0.0), 3),
                             "image": round(max(image_ms, 0.0), 3),
                             "pad": round(max(pad_ms, 0.0), 3)},
        "scan_slab_survivors": surv, "scan_candidates": cands,
        # Per-block readback ledger: what each shape must await before SHA
        # can be PLACED (XLA: packed candidates -> host select -> offsets
        # re-upload; fused: nothing — the cut table D2H overlaps SHA).
        "cand_d2h_bytes_per_block_xla": (1 + 2 * cap_x) * 4,
        "cut_table_d2h_bytes_per_block_fused":
            (cdc_pallas.TABLE_HDR + plan.cap) * 4,
        "serial_awaited_boundaries": {"xla": 2, "fused": 1},
    }))


def bench_multichip(args) -> None:
    """Mesh-plane service-rate curve (ISSUE 9 acceptance): the same
    small-block corpus through parallel/sharded.MeshReducer on sub-meshes
    of 1/2/4/8 devices.  Each coalesced group runs CDC cut selection,
    SHA-256 fingerprinting, and the sharded dedup-bucket probe as ONE
    ledger-visible dispatch ("sharded.step"), so widening the mesh
    multiplies blocks-per-dispatch while the per-step fixed cost (python
    dispatch, transfer setup, readback sync) stays put — per-dispatch
    overhead amortization, the same constant every prior PERF_NOTES round
    measured, and the lever that holds on the emulated CPU mesh too
    (1 vCPU: shard COMPUTE serializes, fixed costs do not — so the
    emulated ratio is capped at d*(F+c)/(F+d*c) for the published
    step_fixed_ms F and step_per_device_ms c; PERF_NOTES round 13 carries
    the decomposition and the real-mesh projection).  Cuts+digests
    are pinned against the native oracle before any timing, and the timed
    full-width pass carries device-ledger evidence that one mesh step ==
    one dispatch.  Prints exactly ONE JSON line."""
    import jax

    from hdrf_tpu import native
    from hdrf_tpu.config import CdcConfig
    from hdrf_tpu.ops.dispatch import gear_mask
    from hdrf_tpu.parallel.sharded import MeshReducer, make_mesh
    from hdrf_tpu.utils import device_ledger

    cdc = CdcConfig(mask_bits=args.mask_bits, min_chunk=args.min_chunk,
                    max_chunk=args.max_chunk)
    mask = gear_mask(cdc)
    devs = jax.devices()
    widths = [d for d in (1, 2, 4, 8) if d <= len(devs)]
    bs = args.block_kb << 10
    rng = np.random.default_rng(23)
    blocks = []
    for _ in range(args.blocks):
        a = rng.integers(0, 256, size=bs, dtype=np.uint8)
        a[: bs // 2] = rng.integers(97, 123, size=bs // 2, dtype=np.uint8)
        blocks.append(a)

    def reducer(d: int) -> MeshReducer:
        mesh = make_mesh(n_data=d, n_seq=1, devices=devs[:d])
        return MeshReducer(cdc, mesh=mesh, lanes_per_device=args.lanes)

    # pin vs the native oracle on the full-width mesh before any timing
    r_full = reducer(widths[-1])
    got = r_full.reduce_many(blocks[: r_full.max_group()])
    oracle_ok = True
    for a, (cuts, digs, _probe) in zip(blocks, got):
        ref_cuts = native.cdc_chunk(a, mask, cdc.min_chunk, cdc.max_chunk)
        starts = np.concatenate([[0], ref_cuts[:-1]]).astype(np.uint64)
        ref_digs = native.sha256_batch(
            a, starts, (ref_cuts - starts).astype(np.uint64))
        oracle_ok &= bool(np.array_equal(cuts, ref_cuts)
                          and np.array_equal(digs, ref_digs))

    def timed(r: MeshReducer):
        g = r.max_group()
        groups = [blocks[at:at + g] for at in range(0, len(blocks), g)]
        for grp in groups:        # warm: jit compile + page in
            r.finish_many(r.submit_many(grp))
        evs = device_ledger.events_snapshot()
        id0 = evs[-1]["id"] if evs else 0
        steps = 0
        t0 = time.perf_counter()
        for _ in range(args.repeats):
            inflight = None
            for grp in groups:    # depth-2 pipelining, write-path style
                nxt = r.submit_many(grp)
                steps += 1
                if inflight is not None:
                    r.finish_many(inflight)
                inflight = nxt
            r.finish_many(inflight)
        dt = time.perf_counter() - t0
        enq = [e for e in device_ledger.events_snapshot()
               if e["id"] > id0 and e["kind"] == "enqueue"]
        disp = sum(1 for e in enq if e["op"] == "sharded.step")
        foreign = sum(1 for e in enq
                      if e["op"] not in ("sharded.step",
                                         "sharded.bucket_refresh"))
        rate = args.repeats * len(blocks) * bs / dt / 2**20
        return rate, dt / steps * 1e3, steps, disp, foreign

    rates: dict[int, float] = {}
    step_ms: dict[int, float] = {}
    steps_full = disp_full = foreign_full = 0
    for d in widths:
        r = r_full if d == widths[-1] else reducer(d)
        rate, per_step, steps, disp, foreign = timed(r)
        rates[d] = rate
        step_ms[d] = per_step
        if d == widths[-1]:
            steps_full, disp_full, foreign_full = steps, disp, foreign
    # Two-point fit of step_time(d) = fixed + d * per_device: on the
    # emulated mesh shard compute serializes onto the one vCPU, so the
    # curve's ceiling is d*(F+c)/(F+d*c) — publishing F and c makes the
    # ratio reproducible and shows what a real mesh (per-device compute
    # parallel, F ~ the 100 ms awaited-dispatch tunnel tax) unlocks.
    dmax = widths[-1]
    c_fit = ((step_ms[dmax] - step_ms[1]) / (dmax - 1)
             if dmax > 1 else 0.0)
    print(json.dumps({
        "op": "multichip mesh reduction plane [service-rate curve]",
        "backend": jax.default_backend(),
        "devices": dmax, "blocks": args.blocks,
        "block_kb": args.block_kb, "lanes_per_device": args.lanes,
        "oracle_ok": oracle_ok,
        "MBps": {str(d): round(v, 2) for d, v in rates.items()},
        "ratio_8v1": round(rates[dmax] / rates[1], 2),
        "step_ms": {str(d): round(v, 3) for d, v in step_ms.items()},
        "step_fixed_ms": round(step_ms[1] - c_fit, 3),
        "step_per_device_ms": round(c_fit, 3),
        "steps": steps_full, "step_dispatches": disp_full,
        "one_dispatch_per_step": bool(steps_full == disp_full
                                      and foreign_full == 0),
    }))


def bench_churn(args) -> None:
    """Long-horizon churn scenario (ISSUE 17 tentpole d; ROADMAP item 1's
    "storage_ratio and read p95 over time is the honest production
    number").  Drives a delete-heavy / rewrite lifecycle over a 1-DN
    MiniCluster: every round writes a generation of dedup-friendly files
    (a shared tile plus a unique tail), deletes a fraction of the oldest
    generation, rewrites a fraction of the survivors, reads everything
    still live, runs one scrub census cycle, and takes one deterministic
    flight-recorder sample (utils/flight_recorder.py sample_once — the
    thread is cadence, never semantics).

    Deletes shrink the DN's LOGICAL footprint (replica block report)
    while the already-sealed containers keep their PHYSICAL bytes, so the
    storage_ratio curve (physical/logical, server/datanode.py
    _flight_sample) degrades UPWARD round over round and the scrub census
    counts the dead chunks as garbage_bytes — the trend report
    (tools/slo_report.py trend) must flag it REGRESS_UP.  Prints exactly
    ONE JSON line: the per-metric first/last/slope curve summary plus the
    trend verdict."""
    import random

    from hdrf_tpu.testing.minicluster import MiniCluster
    from hdrf_tpu.tools import slo_report

    rng = random.Random(0x17)
    kb = args.kb
    shared = bytes(rng.getrandbits(8) for _ in range(kb << 10))

    def payload() -> bytes:
        return shared + bytes(rng.getrandbits(8) for _ in range(kb << 10))

    samples: list[dict] = []
    live: list[str] = []
    gen = 0
    with MiniCluster(n_datanodes=1, replication=1) as mc:
        dn = mc.datanodes[0]
        with mc.client("churn") as c:
            for _ in range(args.rounds):
                for i in range(args.files):
                    path = f"/churn/g{gen}/f{i}"
                    c.write(path, payload(), scheme="dedup_lz4")
                    live.append(path)
                gen += 1
                ndel = int(len(live) * args.delete_frac)
                for path in live[:ndel]:
                    c.delete(path)
                live = live[ndel:]
                nrw = int(len(live) * args.rewrite_frac)
                for path in live[:nrw]:
                    c.delete(path)
                    c.write(path, payload(), scheme="dedup_lz4")
                # deletes reach the DN as invalidate commands riding
                # heartbeats (~0.2 s in MiniCluster): wait for the
                # replica count to settle so the logical census is honest
                deadline = time.monotonic() + 5.0
                while (len(dn.replicas.block_ids()) > len(live)
                       and time.monotonic() < deadline):
                    time.sleep(0.05)
                for path in live:
                    c.read(path)
                dn.scrubber.run_cycle()
                samples.append(dn.flight.sample_once())
    curves = {}
    for metric in ("storage_ratio", "garbage_bytes",
                   "chunk_cache_hit_ratio", "read_p95_ms"):
        vals = [float(s.get(metric, 0.0)) for s in samples]
        curves[metric] = {"first": vals[0], "last": vals[-1],
                          "slope": slo_report.slope(vals),
                          "series": vals}
    tr = slo_report.trend(samples)
    print(json.dumps({
        "op": "churn [delete/rewrite lifecycle, flight-sampled]",
        "rounds": args.rounds,
        "files_per_round": args.files,
        "kb": kb,
        "delete_frac": args.delete_frac,
        "rewrite_frac": args.rewrite_frac,
        "samples": len(samples),
        "curves": curves,
        "regressions": tr["regressions"],
        "verdict": tr["verdict"],
    }))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="hdrf-bench")
    sub = p.add_subparsers(dest="which", required=True)
    d = sub.add_parser("nn")
    d.add_argument("--ops", type=int, default=2000,
                   help="total data ops (create+addBlock+complete chains)")
    d.add_argument("--clients", type=int, default=8,
                   help="concurrent wire clients")
    d.add_argument("--meta-per-op", type=int, default=3,
                   help="stat/getBlockLocations/listing calls per data op")
    d.add_argument("--files", type=int, default=100,
                   help="files per listing directory (rotation width)")
    d.add_argument("--observer-ab", action="store_true",
                   help="paired A/B: storm with vs without observer reads")
    d.add_argument("--kill-active", action="store_true",
                   help="kill the active mid-storm; observers keep serving")
    d.add_argument("--observers", type=int, default=1,
                   help="observer NNs in --observer-ab/--kill-active modes")
    d.add_argument("--rounds", type=int, default=5,
                   help="paired rounds to median over (--observer-ab)")
    d.add_argument("--duration", type=float, default=6.0,
                   help="storm duration in seconds (--kill-active)")
    d.set_defaults(fn=bench_nn)
    d = sub.add_parser("dfs")
    d.add_argument("--mb", type=int, default=64)
    d.add_argument("--datanodes", type=int, default=3)
    d.add_argument("--replication", type=int, default=2)
    d.add_argument("--schemes", default="direct,lz4,dedup_lz4")
    d.add_argument("--pipeline-ab", action="store_true",
                   help="paired multi-stream A/B: pipeline_depth=1 vs "
                        "--depth; one JSON line with the median speedup")
    d.add_argument("--streams", type=int, default=4)
    d.add_argument("--rounds", type=int, default=5)
    d.add_argument("--depth", type=int, default=4)
    d.add_argument("--backend", default="native",
                   help="DN in-process backend for --pipeline-ab")
    d.set_defaults(fn=bench_dfs)
    d = sub.add_parser("ec")
    d.add_argument("--mb", type=int, default=48)
    d.add_argument("--policy", default="rs-6-3-64k")
    d.add_argument("--inner", type=int, default=4,
                   help="k for the slope method's long pass")
    d.add_argument("--repair-ab", action="store_true",
                   help="paired repair A/B: full-gather decode vs coded "
                        "partial-sum exchange, oracle-pinned on every "
                        "erasure pattern; one JSON line")
    d.add_argument("--dns", type=int, default=5,
                   help="simulated holder count for --repair-ab")
    d.add_argument("--erasures", type=int, default=1,
                   help="stripes lost in the --repair-ab timed pattern")
    d.set_defaults(fn=bench_ec)
    d = sub.add_parser("reduction")
    d.add_argument("--mb", type=int, default=64)
    d.add_argument("--backend", default="auto")
    d.set_defaults(fn=bench_reduction)
    d = sub.add_parser("sort")
    d.add_argument("--tiles", type=int, default=8)
    d.add_argument("--entries", type=int, default=1 << 15)
    d.add_argument("--inner", type=int, default=8,
                   help="k for the slope method's long pass")
    d.add_argument("--repeats", type=int, default=5)
    d.add_argument("--interpret", action="store_true",
                   help="run the Pallas kernel through the interpreter "
                        "(correctness spot-check on the CPU mesh)")
    d.set_defaults(fn=bench_sort)
    d = sub.add_parser("cdc")
    d.add_argument("--mb", type=int, default=16)
    d.add_argument("--inner", type=int, default=4,
                   help="k for the slope method's long pass")
    d.add_argument("--repeats", type=int, default=3)
    d.add_argument("--interpret", action="store_true",
                   help="force the fused kernel through the Pallas "
                        "interpreter (correctness-grade timing)")
    d.add_argument("--mask-bits", type=int, default=13,
                   help="geometry sweep: expected chunk size 2^mask_bits")
    d.add_argument("--min-size", type=int, default=2048,
                   help="geometry sweep: CDC min chunk size (bytes)")
    d.add_argument("--no-skip-ahead", dest="skip_ahead",
                   action="store_false",
                   help="pin the PR 4 fused scan alone (drops the "
                        "skip-ahead leg of the A/B)")
    d.set_defaults(fn=bench_cdc)
    d = sub.add_parser("multichip")
    d.add_argument("--blocks", type=int, default=64)
    # Defaults are the dispatch-bound geometry (2 KiB blocks, single-SHA
    # -leg chunks): per-device compute is as thin as the kernels allow,
    # so the curve isolates what widening the mesh buys per step.  Bigger
    # blocks push every width into the 1-vCPU compute wall and flatten
    # the curve without telling you anything new (PERF_NOTES round 13).
    d.add_argument("--block-kb", type=int, default=2)
    d.add_argument("--lanes", type=int, default=1,
                   help="per-device lane capacity (blocks per device "
                        "per mesh step)")
    d.add_argument("--repeats", type=int, default=3)
    d.add_argument("--mask-bits", type=int, default=6)
    d.add_argument("--min-chunk", type=int, default=32)
    d.add_argument("--max-chunk", type=int, default=112)
    d.set_defaults(fn=bench_multichip)
    d = sub.add_parser("recon")
    d.add_argument("--mb", type=int, default=64)
    d.add_argument("--repeats", type=int, default=3)
    d.add_argument("--chunk-kb", type=int, default=0,
                   help="target avg chunk KiB (0 = config default ~8)")
    d.set_defaults(fn=bench_recon)
    d = sub.add_parser("churn")
    d.add_argument("--rounds", type=int, default=6,
                   help="churn generations (one flight sample each)")
    d.add_argument("--files", type=int, default=6,
                   help="files written per generation")
    d.add_argument("--kb", type=int, default=64,
                   help="shared-tile and unique-tail size per file (KiB)")
    d.add_argument("--delete-frac", type=float, default=0.4,
                   help="fraction of the oldest live files deleted per "
                        "round")
    d.add_argument("--rewrite-frac", type=float, default=0.2,
                   help="fraction of survivors rewritten per round")
    d.set_defaults(fn=bench_churn)
    args = p.parse_args(argv)
    args.fn(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
