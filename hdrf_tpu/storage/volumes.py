"""Multi-volume DataNode storage (FsVolumeImpl / FsVolumeList analog).

Re-expresses the reference's per-volume dataset layer —
``fsdataset/impl/FsVolumeImpl.java`` (one volume per configured data dir,
each with its own storage type), ``FsVolumeList`` (round-robin +
available-space placement across volumes), ``DataNode.handleVolumeFailures``
(a failed volume is ejected, the node survives) — and a DiskBalancer-lite
intra-node move planner (``server/diskbalancer/``'s GreedyPlanner, scoped
to replica files).

Layout (storage layout v2, storage/version.py)::

    <data_dir>/volumes/vol-<i>/replicas/...     one ReplicaStore per volume
    <data_dir>/volumes/vol-<i>/containers/...   one ContainerStore per volume
    <data_dir>/index/                           ONE chunk index per DN

Container ids are namespaced per volume (``vol_id << CID_SHIFT``) so the
DN-wide chunk index routes any cid to its volume with a shift — the same
trick the reference uses to namespace container ids by writer thread
(``utilities.java:36-75``'s 2-bit threadID field in its 3-byte ids).

Volume failure semantics: ``eject(vol_id)`` drops the volume's replicas
from reports (the NameNode re-replicates them from healthy peers) and
fails reads of its bytes loudly; the DataNode keeps serving from the
surviving volumes and exits only when the LAST volume dies — the
reference's ``dfs.datanode.failed.volumes.tolerated`` behavior.
"""

from __future__ import annotations

import os
import threading

from hdrf_tpu.storage.container_store import ContainerStore
from hdrf_tpu.storage.replica_store import BlockMeta, ReplicaStore
from hdrf_tpu.utils import metrics

_M = metrics.registry("volumes")

CID_SHIFT = 24          # volume id lives above bit 24 of a container id


class Volume:
    def __init__(self, vol_id: int, root: str, storage_type: str,
                 container_kw: dict):
        self.vol_id = vol_id
        self.storage_type = storage_type
        self.failed = False
        if storage_type == "RAM_DISK" and os.access("/dev/shm", os.W_OK):
            # shm-backed volume (RamDiskReplicaTracker.java:38's tmpfs
            # requirement): bytes live in RAM, persist across DN restarts,
            # vanish on machine reboot — which is why the lazy writer
            # exists.  The dir is keyed to the CONFIGURED root so a
            # restarted DN finds its RAM replicas; an ``origin`` marker
            # lets test harnesses reclaim leaked segments.
            import hashlib
            tag = hashlib.sha1(os.path.abspath(root).encode()).hexdigest()[:16]
            shm = os.path.join("/dev/shm", f"hdrf-ram-{tag}")
            os.makedirs(shm, exist_ok=True)
            with open(os.path.join(shm, "origin"), "w") as f:
                f.write(os.path.abspath(root))
            root = shm
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.replicas = ReplicaStore(os.path.join(root, "replicas"))
        self.containers = ContainerStore(
            os.path.join(root, "containers"),
            id_base=vol_id << CID_SHIFT, **container_kw)

    def free_estimate(self) -> int:
        """Free bytes on the volume's filesystem (capacity heuristic for
        placement; volumes sharing one fs in tests just compare usage)."""
        try:
            st = os.statvfs(self.root)
            free = st.f_bavail * st.f_frsize
        except OSError:
            free = 0
        # subtract what THIS volume already holds so same-fs volumes
        # still spread by usage
        return free - self.used_bytes()

    def used_bytes(self) -> int:
        return (self.replicas.physical_bytes()
                + self.containers.physical_bytes())


class VolumeSet:
    """The DataNode's dataset over N volumes: ReplicaStore-compatible
    surface routed by a block -> volume map, type-aware placement for new
    replicas, container routing by cid namespace, ejection, and the
    intra-DN balancer."""

    def __init__(self, data_dir: str, types: list[str], container_kw: dict):
        assert types, "at least one volume"
        assert len(types) < (1 << 8), "volume count bounded by cid namespace"
        self._lock = threading.Lock()
        self.volumes = [
            Volume(i, os.path.join(data_dir, "volumes", f"vol-{i}"), t,
                   container_kw)
            for i, t in enumerate(types)]
        self._where: dict[int, int] = {}     # block_id -> vol_id
        self._rr = 0
        best_gs: dict[int, int] = {}
        for v in self.volumes:
            for bid, gs, _ln in v.replicas.block_report():
                # the lazy writer leaves shadow copies on DISK: ownership
                # after restart goes to the HIGHEST generation (scan-order
                # would let a stale shadow win and the next lazy tick
                # would then delete the newer RAM copy as "stale")
                if bid not in best_gs or gs > best_gs[bid]:
                    best_gs[bid] = gs
                    self._where[bid] = v.vol_id
        self._containers = MultiContainerStore(self)

    # ------------------------------------------------------------ routing

    def _vol_of(self, block_id: int) -> Volume | None:
        vid = self._where.get(block_id)
        if vid is None or self.volumes[vid].failed:
            return None
        return self.volumes[vid]

    def _alive(self) -> list[Volume]:
        return [v for v in self.volumes if not v.failed]

    def volume_of_cid(self, cid: int) -> Volume:
        vid = cid >> CID_SHIFT
        if vid >= len(self.volumes):
            # the DN-wide index persists cids across restarts; a DN
            # reconfigured with FEWER volumes must degrade (block treated
            # as lost -> re-replicated), not crash on the stale namespace
            raise IOError(f"container {cid}: volume {vid} not configured")
        v = self.volumes[vid]
        if v.failed:
            # an ejected volume's bytes may be corrupt — refuse loudly so
            # the read path degrades to "chunk lost" instead of serving them
            raise IOError(f"container {cid}: volume {vid} is ejected")
        return v

    # ----------------------------------------------------- replica surface

    def _choose_volume(self, storage_type: str | None,
                       exclude_ram: bool = False) -> Volume:
        """Type match first (the NameNode's slot hint), then the volume
        with the most free space among candidates; round-robin breaks
        ties (FsVolumeList's AvailableSpaceVolumeChoosingPolicy over the
        round-robin default)."""
        alive = self._alive()
        if exclude_ram:
            alive = [v for v in alive if v.storage_type != "RAM_DISK"]
            if not alive:
                # NEVER fall back to RAM for shared chunk containers: a
                # reboot would corrupt every referencing block — refuse
                # and let the write degrade to re-replication elsewhere
                raise IOError("no non-RAM volume available for containers")
        if not alive:
            raise IOError("all volumes failed")
        cands = [v for v in alive if v.storage_type == storage_type] or alive
        with self._lock:
            self._rr += 1
            start = self._rr
        best = max(cands, key=lambda v: (v.free_estimate(),
                                         -((start + v.vol_id) % len(cands))))
        return best

    def create_rbw(self, block_id: int, gen_stamp: int = 0,
                   storage_type: str | None = None):
        vol = self._vol_of(block_id) or self._choose_volume(storage_type)
        writer = vol.replicas.create_rbw(block_id, gen_stamp)
        with self._lock:
            self._where[block_id] = vol.vol_id
        return writer

    def get_meta(self, block_id: int) -> BlockMeta | None:
        v = self._vol_of(block_id)
        return v.replicas.get_meta(block_id) if v else None

    def is_rbw(self, block_id: int) -> bool:
        v = self._vol_of(block_id)
        return v.replicas.is_rbw(block_id) if v else False

    def read_data(self, block_id: int, offset: int = 0,
                  length: int = -1) -> bytes:
        for attempt in range(2):
            v = self._vol_of(block_id)
            if v is None:
                raise IOError(f"block {block_id}: no live volume holds it")
            try:
                return v.replicas.read_data(block_id, offset, length)
            except FileNotFoundError:
                # lazy-persist eviction raced us: _where already points at
                # the disk copy — re-resolve once
                if attempt:
                    raise
        raise IOError(f"block {block_id}: unreadable")  # pragma: no cover

    def data_path(self, block_id: int) -> str:
        v = self._vol_of(block_id)
        if v is None:
            raise IOError(f"block {block_id}: no live volume holds it")
        return v.replicas.data_path(block_id)

    def truncate_replica(self, block_id: int, new_len: int,
                         new_gs: int | None = None) -> bool:
        v = self._vol_of(block_id)
        return v.replicas.truncate_replica(block_id, new_len,
                                           new_gs=new_gs) if v else False

    def delete(self, block_id: int) -> None:
        # sweep EVERY volume, not just the owner: the lazy writer keeps
        # shadow disk copies of RAM replicas, and an owner-only delete
        # would orphan them
        for v in self._alive():
            if v.replicas.get_meta(block_id) is not None \
                    or v.replicas.is_rbw(block_id):
                v.replicas.delete(block_id)
        with self._lock:
            self._where.pop(block_id, None)

    def block_ids(self) -> list[int]:
        out: list[int] = []
        for v in self._alive():
            out.extend(bid for bid in v.replicas.block_ids()
                       if self._where.get(bid) == v.vol_id)
        return out

    def block_report(self) -> list[tuple[int, int, int, str]]:
        """(block_id, gen_stamp, logical_len, storage_type) per replica —
        the reference reports per-storage (DatanodeStorageInfo), which is
        what lets the NameNode see each replica's actual type on
        multi-type nodes.  Only the OWNING volume's copy is reported: the
        lazy writer keeps shadow disk copies of RAM replicas, and a
        double row for one block would confuse the NN's replica count."""
        out = []
        for v in self._alive():
            out.extend((bid, gs, ln, v.storage_type)
                       for bid, gs, ln in v.replicas.block_report()
                       if self._where.get(bid) == v.vol_id)
        return out

    def scan(self) -> list[str]:
        out: list[str] = []
        for v in self._alive():
            out.extend(v.replicas.scan())
        return out

    def physical_bytes(self) -> int:
        return sum(v.replicas.physical_bytes() for v in self._alive())

    # --------------------------------------------------- container surface

    @property
    def containers(self) -> "MultiContainerStore":
        return self._containers

    # ------------------------------------------------------------ failure

    def eject(self, vol_id: int) -> list[int]:
        """Volume died (DataNode.handleVolumeFailures): drop it from
        service.  Its replicas vanish from subsequent reports — the
        NameNode re-replicates them from healthy peers; its containers'
        chunks surface as lost through the scanner/read path.  Returns
        the block ids that went away."""
        v = self.volumes[vol_id]
        if v.failed:
            return []
        v.failed = True
        with self._lock:
            affected = [bid for bid, vid in self._where.items()
                        if vid == vol_id]
            lost = []
            for bid in affected:
                # a lazy-persisted shadow on a surviving volume rescues
                # the block (RAM volume death is the exact scenario the
                # lazy writer exists for) — fail ownership over instead
                # of declaring it lost.  Only a CURRENT-generation shadow
                # counts: serving a stale pre-append copy silently would
                # be worse than re-replicating from a healthy peer.
                lost_meta = v.replicas.get_meta(bid)
                lost_gs = lost_meta.gen_stamp if lost_meta else 0
                for sv in self.volumes:
                    if sv.failed or sv.vol_id == vol_id:
                        continue
                    sm = sv.replicas.get_meta(bid)
                    if sm is not None and sm.gen_stamp >= lost_gs:
                        self._where[bid] = sv.vol_id
                        _M.incr("blocks_rescued_by_shadow")
                        break
                else:
                    self._where.pop(bid, None)
                    lost.append(bid)
        _M.incr("volumes_ejected")
        _M.incr("blocks_lost_to_volume_failure", len(lost))
        return lost

    def alive_count(self) -> int:
        return len(self._alive())

    # ------------------------------------------------------- lazy persist

    def lazy_persist_tick(self, ram_capacity: int) -> tuple[int, int]:
        """One lazy-writer pass (RamDiskReplicaTracker.java:38 +
        LazyWriter semantics): every finalized replica on a RAM_DISK
        volume gets a shadow copy on a DISK volume (the durability half);
        then, while the RAM volume exceeds ``ram_capacity``, persisted
        replicas are EVICTED — ownership flips to the disk copy and the
        RAM bytes are reclaimed.  Reads keep hitting RAM until eviction
        (the fast-read half).  Returns (persisted, evicted)."""
        rams = [v for v in self._alive() if v.storage_type == "RAM_DISK"]
        disks = [v for v in self._alive() if v.storage_type != "RAM_DISK"]
        if not rams or not disks:
            return (0, 0)
        persisted = evicted = 0
        for rv in rams:
            for bid, gs, _ln in rv.replicas.block_report():
                if self._where.get(bid) != rv.vol_id:
                    # stale RAM copy (evicted or superseded): reclaim
                    rv.replicas.delete(bid)
                    continue
                if rv.replicas.is_rbw(bid):
                    continue
                meta = rv.replicas.get_meta(bid)
                if meta is None:
                    continue
                # an up-to-date shadow on ANY disk satisfies persistence —
                # re-checking only the currently-most-free disk would
                # duplicate the shadow each time that choice flips
                if any(dm is not None and dm.gen_stamp >= meta.gen_stamp
                       for dm in (dv.replicas.get_meta(bid)
                                  for dv in disks)):
                    continue
                dv = max(disks, key=lambda v: v.free_estimate())
                dv.replicas.adopt(meta, rv.replicas.read_data(bid))
                persisted += 1
                _M.incr("lazy_persisted")
            while rv.used_bytes() > ram_capacity:
                flipped = False
                for bid, gs, _ln in rv.replicas.block_report():
                    if self._where.get(bid) != rv.vol_id:
                        continue
                    for dv in disks:
                        dm = dv.replicas.get_meta(bid)
                        if dm is not None and dm.gen_stamp >= gs:
                            with self._lock:
                                self._where[bid] = dv.vol_id
                            rv.replicas.delete(bid)
                            evicted += 1
                            flipped = True
                            _M.incr("lazy_evicted")
                            break
                    if flipped:
                        break
                if not flipped:
                    break   # nothing evictable yet (unpersisted writes)
        return persisted, evicted

    # ----------------------------------------------------- disk balancer

    def plan_moves(self, threshold: float = 0.10) -> list[tuple[int, int, int]]:
        """GreedyPlanner-lite: while the spread between the fullest and
        emptiest live volume exceeds ``threshold`` of the fullest's used
        bytes, move the largest movable replica down the gradient.
        Returns (block_id, from_vol, to_vol) steps.  Only replicas with
        physical bytes move (dedup'd replicas are 0-byte pointers; their
        bytes live in chunk containers)."""
        vols = self._alive()
        if len(vols) < 2:
            return []
        used = {v.vol_id: float(v.used_bytes()) for v in vols}
        sizes: dict[int, list[tuple[int, int]]] = {}
        for v in vols:
            rows = []
            for m in v.replicas.block_report():
                meta = v.replicas.get_meta(m[0])  # may race a delete
                if meta is not None and meta.physical_len > 0:
                    rows.append((m[2], m[0]))
            sizes[v.vol_id] = sorted(rows, reverse=True)
        plan: list[tuple[int, int, int]] = []
        for _ in range(1000):
            hi = max(used, key=lambda k: used[k])
            lo = min(used, key=lambda k: used[k])
            if used[hi] <= 0 or (used[hi] - used[lo]) <= threshold * used[hi]:
                break
            movable = sizes[hi]
            if not movable:
                break
            size, bid = movable.pop(0)
            if size > (used[hi] - used[lo]) / 2 and len(movable):
                # moving the biggest would overshoot: try the best fit
                fit = next((i for i, (s, _) in enumerate(movable)
                            if s <= (used[hi] - used[lo]) / 2), None)
                if fit is not None:
                    movable.insert(0, (size, bid))
                    size, bid = movable.pop(fit + 1)
            plan.append((bid, hi, lo))
            used[hi] -= size
            used[lo] += size
            sizes[lo].append((size, bid))
        return plan

    def execute_moves(self, plan: list[tuple[int, int, int]]) -> int:
        """Apply planner steps: copy data+meta into the target volume,
        flip the routing map, delete the source copy.  Readers route by
        the map, so the switch is atomic from their view."""
        done = 0
        for bid, src_vid, dst_vid in plan:
            src, dst = self.volumes[src_vid], self.volumes[dst_vid]
            if src.failed or dst.failed:
                continue
            meta = src.replicas.get_meta(bid)
            if meta is None or src.replicas.is_rbw(bid):
                continue
            data = src.replicas.read_data(bid)
            dst.replicas.adopt(meta, data)
            with self._lock:
                self._where[bid] = dst_vid
            src.replicas.delete(bid)
            done += 1
            _M.incr("replicas_moved_intra_dn")
        return done


class MultiContainerStore:
    """ContainerStore surface over all live volumes, routing by the cid's
    volume namespace; appends go to the volume with the most free space."""

    def __init__(self, vs: VolumeSet):
        self._vs = vs

    def append_chunks(self, chunks, on_seal=None, sync: bool = True):
        # chunk containers hold SHARED dedup bytes: never place them on a
        # RAM_DISK volume (a reboot would corrupt every referencing block)
        vol = self._vs._choose_volume(None, exclude_ram=True)
        return vol.containers.append_chunks(chunks, on_seal=on_seal,
                                            sync=sync)

    def append_ranges(self, data, starts, lens, on_seal=None,
                      sync: bool = True):
        vol = self._vs._choose_volume(None, exclude_ram=True)
        return vol.containers.append_ranges(data, starts, lens,
                                            on_seal=on_seal, sync=sync)

    def sync_lanes(self) -> None:
        for v in self._vs._alive():
            v.containers.sync_lanes()

    def read_container(self, cid: int) -> bytes:
        return self._vs.volume_of_cid(cid).containers.read_container(cid)

    def read_chunks(self, locs):
        by_vol: dict[int, list[int]] = {}
        for i, (cid, _, _) in enumerate(locs):
            by_vol.setdefault(cid >> CID_SHIFT, []).append(i)
        out = [None] * len(locs)
        for vid, idxs in by_vol.items():
            # route through volume_of_cid so stale cid namespaces and
            # ejected volumes raise IOError (treat-as-lost), not IndexError
            vol = self._vs.volume_of_cid(vid << CID_SHIFT)
            got = vol.containers.read_chunks([locs[i] for i in idxs])
            for i, b in zip(idxs, got):
                out[i] = b
        return out

    def read_containers(self, cids, decompress_batch=None):
        by_vol: dict[int, list[int]] = {}
        for cid in cids:
            by_vol.setdefault(cid >> CID_SHIFT, []).append(cid)
        out: dict[int, bytes] = {}
        for vid, ids in by_vol.items():
            vol = self._vs.volume_of_cid(vid << CID_SHIFT)
            out.update(vol.containers.read_containers(
                ids, decompress_batch=decompress_batch))
        return out

    def copy_live(self, cid: int, live, on_seal=None):
        # live chunks move into the OWNING volume's open lane (compaction
        # stays intra-volume so cids keep routing correctly)
        return self._vs.volume_of_cid(cid).containers.copy_live(
            cid, live, on_seal=on_seal)

    def delete_container(self, cid: int) -> None:
        self._vs.volume_of_cid(cid).containers.delete_container(cid)

    def quarantine(self, cid: int) -> int:
        return self._vs.volume_of_cid(cid).containers.quarantine(cid)

    def sealed_file_bytes(self, cid: int) -> bytes | None:
        return self._vs.volume_of_cid(cid).containers.sealed_file_bytes(cid)

    def drop_sealed_file(self, cid: int) -> int:
        return self._vs.volume_of_cid(cid).containers.drop_sealed_file(cid)

    def has_container(self, cid: int, need_bytes: int = 0) -> bool:
        try:
            v = self._vs.volume_of_cid(cid)
        except IOError:
            return False   # stale namespace (volume removed): lost
        return (not v.failed) and v.containers.has_container(cid, need_bytes)

    def container_ids(self) -> list[int]:
        out: list[int] = []
        for v in self._vs._alive():
            out.extend(v.containers.container_ids())
        return sorted(out)

    def flush_open(self, on_seal=None) -> None:
        for v in self._vs._alive():
            v.containers.flush_open(on_seal=on_seal)

    def enable_async_seals(self) -> None:
        for v in self._vs._alive():
            v.containers.enable_async_seals()

    def drain_seals(self) -> None:
        for v in self._vs._alive():
            v.containers.drain_seals()

    def close_async_seals(self) -> None:
        for v in self._vs._alive():
            v.containers.close_async_seals()

    def physical_bytes(self) -> int:
        return sum(v.containers.physical_bytes() for v in self._vs._alive())

    def container_sizes(self) -> dict[int, int]:
        out: dict[int, int] = {}
        for v in self._vs._alive():
            out.update(v.containers.container_sizes())
        return out

    @property
    def _on_delete(self):
        return self._vs.volumes[0].containers._on_delete

    @_on_delete.setter
    def _on_delete(self, fn) -> None:
        for v in self._vs.volumes:
            v.containers._on_delete = fn

    @property
    def _on_retire(self):
        return self._vs.volumes[0].containers._on_retire

    @_on_retire.setter
    def _on_retire(self, fn) -> None:
        # the decoded-chunk cache is DN-wide (server/read_plane.py), so one
        # retirement hook covers every volume's store
        for v in self._vs.volumes:
            v.containers._on_retire = fn

    @property
    def _stripe_fallback(self):
        return self._vs.volumes[0].containers._stripe_fallback

    @_stripe_fallback.setter
    def _stripe_fallback(self, fn) -> None:
        # stripes are DN-wide (stripe_store.py keys by owner dn_id), so one
        # fallback serves every volume's store
        for v in self._vs.volumes:
            v.containers._stripe_fallback = fn

    @property
    def _stripe_probe(self):
        return self._vs.volumes[0].containers._stripe_probe

    @_stripe_probe.setter
    def _stripe_probe(self, fn) -> None:
        for v in self._vs.volumes:
            v.containers._stripe_probe = fn
