"""Provided-storage alias map (block -> external byte range).

Re-expression of the reference's provided-storage plumbing —
``server/aliasmap/InMemoryAliasMap.java`` (block -> ProvidedStorageLocation
over LevelDB), ``server/common/FileRegion.java:34`` (the (Block,
ProvidedStorageLocation) pair), and the PROVIDED StorageType whose replicas'
bytes live in an external store rather than on DataNode disks — as a
msgpack-persisted map the DataNode consults when a read misses its local
replica set.

The reference generates alias maps offline with the fsimage image-writer;
here ``dfsadmin -provide`` drives the live flow: the NameNode journals the
namespace half (a complete file whose blocks are provided), the CLI pushes
the FileRegions to every DataNode (the ``alias_add`` op), and DNs persist +
report them as PROVIDED replicas, so reads route like any other block.
Only ``file://`` URIs resolve in this environment; other schemes raise at
read time (the mount is still registered — a deployment with an object-store
fetcher plugs in at ``_open_uri``).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass

import msgpack

from hdrf_tpu.utils import metrics

_M = metrics.registry("aliasmap")


@dataclass
class FileRegion:
    """One provided block: bytes [offset, offset+length) of ``uri``
    (FileRegion.java:34 / ProvidedStorageLocation)."""

    block_id: int
    uri: str
    offset: int
    length: int

    def pack(self) -> list:
        return [self.block_id, self.uri, self.offset, self.length]

    @staticmethod
    def unpack(v: list) -> "FileRegion":
        return FileRegion(v[0], v[1], v[2], v[3])


class InMemoryAliasMap:
    """block_id -> FileRegion with write-replace persistence
    (InMemoryAliasMap.java's LevelDB role; the write/list/read protocol
    surface of InMemoryAliasMapProtocol)."""

    def __init__(self, path: str, mount_root: str | None = "/"):
        """``mount_root`` confines every ``file://`` region to one
        directory subtree (symlinks resolved): block tokens gate WHO may
        alias blocks, the mount root bounds WHAT they can alias — without
        it a write-token holder aliases a block to any DN-readable local
        file and discloses it through the ordinary read path.  "/" opts
        out of confinement; None/"" disables file:// resolution."""
        self._path = path
        self._mount_root = os.path.realpath(mount_root) if mount_root else None
        self._lock = threading.Lock()
        self._map: dict[int, FileRegion] = {}
        if os.path.exists(path):
            with open(path, "rb") as f:
                for v in msgpack.unpackb(f.read(), raw=False):
                    r = FileRegion.unpack(v)
                    self._map[r.block_id] = r

    def _persist_locked(self) -> None:
        blob = msgpack.packb([r.pack() for r in self._map.values()])
        with open(self._path + ".tmp", "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(self._path + ".tmp", self._path)

    def write(self, regions: list[FileRegion]) -> None:
        with self._lock:
            for r in regions:
                self._map[r.block_id] = r
            self._persist_locked()
        _M.incr("regions_written", len(regions))

    def remove(self, block_ids: list[int]) -> None:
        with self._lock:
            for bid in block_ids:
                self._map.pop(bid, None)
            self._persist_locked()

    def read(self, block_id: int) -> FileRegion | None:
        with self._lock:
            return self._map.get(block_id)

    def list(self) -> list[FileRegion]:
        with self._lock:
            return list(self._map.values())

    # ------------------------------------------------------------ data path

    def check_uri(self, uri: str) -> None:
        """Raise if ``uri`` is not resolvable inside the mount root.
        Called at alias_add time (reject the region before it persists)
        and again at every read (the file may have become a symlink out
        of the tree since)."""
        if not uri.startswith("file://"):
            raise IOError(f"unsupported provided-storage scheme: {uri}")
        if self._mount_root is None:
            _M.incr("mount_root_rejects")
            raise IOError("provided storage disabled: no mount root "
                          "configured (datanode.provided_mount_root)")
        if self._mount_root == os.sep:
            return
        rp = os.path.realpath(uri[len("file://"):])
        if rp != self._mount_root and not rp.startswith(
                self._mount_root + os.sep):
            _M.incr("mount_root_rejects")
            raise IOError(f"provided uri outside mount root: {uri}")

    def _open_uri(self, uri: str):
        self.check_uri(uri)
        return open(uri[len("file://"):], "rb")

    def read_bytes(self, block_id: int, offset: int = 0,
                   length: int = -1) -> bytes | None:
        """Logical bytes of a provided block (None = not provided here).
        Range semantics match ReplicaStore.read_data."""
        region = self.read(block_id)
        if region is None:
            return None
        end = region.length if length < 0 else min(offset + length,
                                                   region.length)
        if offset >= end:
            return b""
        with self._open_uri(region.uri) as f:
            f.seek(region.offset + offset)
            out = f.read(end - offset)
        if len(out) != end - offset:
            raise IOError(f"provided block {block_id}: external store "
                          f"returned {len(out)} of {end - offset} bytes")
        _M.incr("provided_reads")
        return out
