"""Chunk-container store: append-only container files with seal-on-rollover.

Re-expression of the reference's chunk store (threadedStorer,
DataDeduplicator.java:652-845): chunks append to flat files
``<chunkDir>/<containerID>`` up to 32 MB (DataNode.java:434 ``maxSize=2^25``),
and a container is LZ4-compressed when it rolls over
(DataDeduplicator.java:770-781).  Reads group chunks by container, decompress
sealed containers, and slice chunks out (DataConstructor.threadedConstructor,
DataConstructor.java:430-567, open-container fast path :482-490).

Differences by design:

- **Lanes, not threads-with-bit-tricks.** The reference namespaces container
  ids with a 2-bit writer-thread field packed into 3 bytes
  (utilities.java:36-75).  Here container ids are a flat monotonic counter;
  concurrency comes from N independent *lanes*, each owning one open container
  and its own lock.
- **Sealed-ness is self-describing**: ``<cid>.raw`` (open) vs ``<cid>.sealed``
  (codec-framed), no external state needed to read.
- **Compaction exists** (the reference can never reclaim dead chunks).
"""

from __future__ import annotations

import contextlib
import os
import queue
import struct
import threading
from dataclasses import dataclass

from hdrf_tpu.utils import codec as codecs
from hdrf_tpu.utils import fault_injection, metrics

_M = metrics.registry("container_store")


def cache_hit_ratio() -> float:
    """Decoded-container LRU hit ratio over the process's cumulative
    ``cache_hit``/``cache_miss`` counters (0.0 before any probe) — the
    /prom + /health gauge ROADMAP item 1 asks for (the counters existed
    since the true-LRU landed but were never surfaced as a ratio)."""
    hits, misses = _M.counter("cache_hit"), _M.counter("cache_miss")
    total = hits + misses
    return hits / total if total else 0.0


def _gauge_hit_ratio() -> None:
    _M.gauge("cache_hit_ratio", cache_hit_ratio())

_SEAL_HDR = struct.Struct("<IQI")  # magic, usize, codec id
_SEAL_MAGIC = 0x48435452  # "RTCH"
# Open (.raw) containers carry a same-width placeholder header so sealing an
# incompressible container is a header stamp + rename, not a data rewrite.
# The distinct magic makes a mis-framed file a loud error, never a silent
# 16-byte shift of every chunk.
_RAW_MAGIC = 0x48435257  # "WRCH"


@dataclass
class _Lane:
    lock: threading.Lock
    container_id: int = -1
    size: int = 0
    fh: object | None = None
    image: bytearray | None = None  # in-memory mirror of the open container


class ContainerStore:
    """Append-only chunk containers with compress-on-seal and compaction."""

    def __init__(self, directory: str, container_size: int = 1 << 25,
                 lanes: int = 4, codec: str = "lz4", cache_containers: int = 4,
                 compress_fn=None, on_roll=None, fsync: bool = False,
                 id_base: int = 0, compress_batch_fn=None):
        """``compress_fn`` overrides the seal-time compressor while keeping
        the frame codec id (the TPU LZ4 stage produces format-identical
        output, so readers decode with the stock codec either way).
        ``compress_batch_fn(list[bytes]) -> list[bytes]`` is its grouped
        form: when set, ``flush_open`` seals all open lanes through ONE
        call (one device program + one grouped readback on the TPU
        backend) instead of a compressor round trip per lane.
        ``on_roll(cid, payload)`` observes each container's full
        uncompressed payload at seal time (from the open-lane memory
        mirror) — the hook an async seal pipeline hangs off, sparing a disk
        read-back."""
        self._dir = directory
        os.makedirs(directory, exist_ok=True)
        self._container_size = container_size
        self._codec = codec
        self._compress_fn = compress_fn
        self._compress_batch_fn = compress_batch_fn
        self._on_roll = on_roll
        # fsync policy for container DATA (HDFS parity: block data is not
        # fsync'd on finalize — replication is the durability story; see
        # ReductionConfig.fsync_containers).  Seal-time writes of NEW files
        # still fsync regardless (rename barrier).
        self._fsync = fsync
        # observer for container deletion (compaction/GC): lets a device
        # reconstructor drop its stale HBM image
        self._on_delete = None
        # observer for container RETIREMENT (delete OR quarantine): the
        # read plane's decoded-chunk cache drops entries sliced from the
        # container.  Separate from _on_delete because quarantine keeps the
        # container logically present (no HBM/EC teardown) yet its bytes
        # must never serve again, cached slices included.
        self._on_retire = None
        # EC cold tier hooks (storage/stripe_store.py): when a sealed file
        # is gone because the container was demoted to stripes,
        # ``_stripe_fallback(cid)`` returns the reconstructed sealed FILE
        # bytes (header + compressed payload) or None, and
        # ``_stripe_probe(cid)`` returns the uncompressed payload size
        # recorded in the striping manifest (for has_container) or None.
        self._stripe_fallback = None
        self._stripe_probe = None
        self._alloc_lock = threading.Lock()
        # ``id_base`` namespaces this store's container ids (multi-volume
        # DNs: vol_id << CID_SHIFT — the same trick the reference uses to
        # namespace container ids by writer thread, the 2-bit threadID
        # field packed into its 3-byte ids at utilities.java:36-75), so
        # one DN-wide chunk index can route any cid to its volume.
        self._id_base = id_base
        self._next_id = max(self._scan_next_id(), id_base)
        self._lanes = [_Lane(threading.Lock()) for _ in range(lanes)]
        self._rr = 0
        # Tiny LRU of decompressed sealed containers (read amplification guard;
        # the reference re-decompresses the whole container per read).
        self._cache: dict[int, bytes] = {}
        self._cache_cap = cache_containers
        self._cache_lock = threading.Lock()
        # Async seal stage (enable_async_seals): rollover compression moves
        # off the appending thread onto one worker; None = inline seals.
        self._seal_q: queue.Queue | None = None
        self._seal_thread: threading.Thread | None = None
        self._seal_exc: BaseException | None = None

    def _scan_next_id(self) -> int:
        mx = -1
        for name in os.listdir(self._dir):
            stem = name.split(".")[0]
            if stem.isdigit():
                mx = max(mx, int(stem))
        return mx + 1

    def _raw_path(self, cid: int) -> str:
        return os.path.join(self._dir, f"{cid}.raw")

    def _sealed_path(self, cid: int) -> str:
        return os.path.join(self._dir, f"{cid}.sealed")

    # -------------------------------------------------------------- writing

    def append_chunks(self, chunks: list[bytes], on_seal=None,
                      sync: bool = True) -> list[tuple[int, int, int]]:
        """Append chunks to one lane's open container; returns
        (container_id, offset, length) per chunk.  ``on_seal(cid)`` fires after
        a rollover compresses+seals a container (index notification).
        ``sync=False`` skips the fsync — the batched commit pipeline calls
        ``sync_lanes()`` once per group instead, BEFORE the covering index
        commit (same durability ordering, amortized)."""
        if not chunks:  # fully-deduplicated block: nothing new to store
            return []
        with self._alloc_lock:
            lane = self._lanes[self._rr % len(self._lanes)]
            self._rr += 1
        out: list[tuple[int, int, int]] = []
        with lane.lock:
            pending: list[bytes] = []

            def drain():
                if pending:
                    blob = b"".join(pending)
                    if lane.fh is not None:
                        lane.fh.write(blob)
                    lane.image += blob
                    pending.clear()

            for chunk in chunks:
                if lane.image is None or (
                        lane.size + len(chunk) > self._container_size and lane.size > 0):
                    if lane.image is not None:
                        drain()  # before rollover seals the container
                        self._seal_locked(lane, on_seal)
                    self._open_locked(lane)
                off = lane.size
                pending.append(chunk)
                lane.size += len(chunk)
                out.append((lane.container_id, off, len(chunk)))
            # One write per batch, not per chunk (measured: per-chunk writes
            # were ~25% of the whole ingest host cost at 8 KiB avg chunks).
            drain()
            if lane.fh is not None:
                lane.fh.flush()
                if sync and self._fsync:
                    os.fsync(lane.fh.fileno())
        _M.incr("chunks_appended", len(chunks))
        return out

    def append_ranges(self, data, starts, lens, on_seal=None,
                      sync: bool = True) -> list[tuple[int, int, int]]:
        """``append_chunks`` for chunks that are RANGES of one buffer (the
        dedup commit's shape): byte movement runs as one native
        gather_ranges per container segment instead of n memoryview
        slices + list appends + a join — the commit half's Python byte
        shuffling (measured ~1.2 s per 512 MiB of TeraGen-density chunks
        on the 1-vCPU host).  Rollover semantics identical to
        append_chunks: a chunk that doesn't fit seals the open container
        first; an oversized chunk lands alone in an empty one."""
        import numpy as np

        from hdrf_tpu import native

        n = int(len(starts))
        if n == 0:
            return []
        starts = np.ascontiguousarray(starts, dtype=np.uint64)
        lens = np.ascontiguousarray(lens, dtype=np.uint64)
        with self._alloc_lock:
            lane = self._lanes[self._rr % len(self._lanes)]
            self._rr += 1
        out_cid = np.empty(n, np.int64)
        out_off = np.empty(n, np.int64)
        csum = np.concatenate([[0], np.cumsum(lens, dtype=np.int64)])
        with lane.lock:
            i = 0
            while i < n:
                if lane.image is None:
                    self._open_locked(lane)
                cap = self._container_size - lane.size
                j = int(np.searchsorted(csum, csum[i] + cap,
                                        side="right")) - 1
                if j <= i:
                    if lane.size > 0:
                        self._seal_locked(lane, on_seal)
                        self._open_locked(lane)
                        continue
                    j = i + 1
                blob = native.gather_ranges(data, starts[i:j],
                                            lens[i:j]).tobytes()
                if lane.fh is not None:
                    lane.fh.write(blob)
                out_cid[i:j] = lane.container_id
                out_off[i:j] = lane.size + (csum[i:j] - csum[i])
                lane.image += blob
                lane.size += int(csum[j] - csum[i])
                i = j
            if lane.fh is not None:
                lane.fh.flush()
                if sync and self._fsync:
                    os.fsync(lane.fh.fileno())
        _M.incr("chunks_appended", n)
        return [(int(c), int(o), int(ln))
                for c, o, ln in zip(out_cid, out_off, lens)]

    def sync_lanes(self) -> None:
        """Flush (and, under the fsync policy, fsync) every open lane — the
        group-commit durability barrier.  A no-op in memory-resident mode,
        where open containers reach disk once, at seal."""
        for lane in self._lanes:
            with lane.lock:
                if lane.fh is not None:
                    lane.fh.flush()
                    if self._fsync:
                        os.fsync(lane.fh.fileno())

    def _open_locked(self, lane: _Lane) -> None:
        with self._alloc_lock:
            cid = self._next_id
            self._next_id += 1
        lane.container_id = cid
        lane.size = 0
        lane.image = bytearray()
        # Write-through WITHOUT fsync (unless the strict policy is on):
        # process death loses nothing (the page cache survives), OS-crash
        # durability comes from replication — HDFS's own block-data story.
        # Raw files are unlinked at seal, so under steady rollover their
        # data blocks are mostly never written back at all (ext4 ordered
        # mode skips deleted data): container bytes effectively hit the
        # platter once, compressed.
        lane.fh = open(self._raw_path(cid), "wb")
        # Placeholder header: chunk data starts at _SEAL_HDR.size, so sealing
        # an incompressible (or codec "none") container is a header stamp +
        # rename instead of a full data rewrite (measured: the rewrite was
        # ~35% of ingest host cost for codec "none").
        lane.fh.write(_SEAL_HDR.pack(_RAW_MAGIC, 0, 0))

    def _seal_locked(self, lane: _Lane, on_seal, comp=None) -> None:
        had_raw = lane.fh is not None
        if had_raw:
            lane.fh.close()
        # the in-memory mirror spares the seal a full read-back of the file
        # (measured ~10% of ingest host cost at 32 MiB containers)
        payload = bytes(lane.image)
        if self._on_roll is not None:
            self._on_roll(lane.container_id, payload)
        if self._seal_q is not None:
            # Async stage: hand the payload to the seal worker and return —
            # the appending (commit) thread never pays the compressor.  Safe
            # because sealed-ness is self-describing: the raw file stays
            # readable (read_container's raw fallback) until the worker's
            # seal renames it, and the cid is retired from the lane HERE, so
            # no later append can touch it.
            self._seal_q.put((lane.container_id, payload, had_raw, on_seal,
                              comp))
            _M.incr("async_seals")
        else:
            self.seal(lane.container_id, data=payload, have_raw=had_raw,
                      comp=comp)
            if on_seal is not None:
                on_seal(lane.container_id)
        lane.fh = None
        lane.image = None

    def seal(self, cid: int, data: bytes | None = None,
             have_raw: bool | None = None, comp: bytes | None = None) -> None:
        """Compress a raw container into the sealed format (the rollover LZ4
        pass, DataDeduplicator.java:770-781).  ``data`` carries the
        container's chunk bytes when the caller already holds them (the
        open-lane mirror); otherwise they are read from the raw file.
        ``have_raw=False`` (memory-resident lane) writes the sealed file
        directly — there is no raw file to stamp or remove.  ``comp`` is
        the already-compressed payload when the caller ran the compressor
        itself (the grouped flush_open seal)."""
        raw = self._raw_path(cid)
        if have_raw is None:
            have_raw = os.path.exists(raw)
        if have_raw:
            with open(raw, "r+b") as f:
                magic = _SEAL_HDR.unpack(f.read(_SEAL_HDR.size))[0]
                if magic != _RAW_MAGIC:
                    raise IOError(f"container {cid}: bad raw magic {magic:#x}")
                if data is None:
                    data = f.read()
                fault_injection.point("container.seal")
                if comp is None:
                    comp = self._compress(data)
                if len(comp) >= len(data):
                    # Incompressible or codec "none": stamp the placeholder
                    # header in place and rename — no data copy.  The fsync
                    # (forcing the full container's writeback NOW) follows
                    # the block-data durability policy.
                    f.seek(0)
                    f.write(_SEAL_HDR.pack(_SEAL_MAGIC, len(data),
                                           codecs.CODEC_IDS["none"]))
                    f.flush()
                    if self._fsync:
                        os.fsync(f.fileno())
                    os.replace(raw, self._sealed_path(cid))
                    _M.incr("sealed")
                    return
        else:
            assert data is not None, "memory-resident seal needs the payload"
            fault_injection.point("container.seal")
            if comp is None:
                comp = self._compress(data)
        codec = self._codec if len(comp) < len(data) else "none"
        out = comp if len(comp) < len(data) else data
        tmp = self._sealed_path(cid) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_SEAL_HDR.pack(_SEAL_MAGIC, len(data),
                                   codecs.CODEC_IDS[codec]))
            f.write(out)
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
        os.replace(tmp, self._sealed_path(cid))
        if have_raw:
            os.unlink(raw)
        _M.incr("sealed")

    def _compress(self, data: bytes) -> bytes:
        if self._codec == "none":
            return data
        if self._compress_fn is not None:
            return self._compress_fn(data)
        return codecs.compress(self._codec, data)

    def flush_open(self, on_seal=None) -> None:
        """Seal every open lane (shutdown/test hook).

        With ``compress_batch_fn`` set, every sealable lane's payload is
        compressed through ONE batched call before sealing — on the TPU
        backend that is a single device program plus one grouped record
        readback instead of a dispatch/readback round trip per lane."""
        with contextlib.ExitStack() as stack:
            sealable = []
            for lane in self._lanes:
                stack.enter_context(lane.lock)
                if lane.image is not None and lane.size > 0:
                    sealable.append(lane)
                elif lane.image is not None:
                    if lane.fh is not None:
                        lane.fh.close()
                        os.unlink(self._raw_path(lane.container_id))
                        lane.fh = None
                    lane.image = None
            comps = None
            if (self._compress_batch_fn is not None and len(sealable) > 1
                    and self._codec != "none"):
                comps = self._compress_batch_fn(
                    [bytes(l.image) for l in sealable])
                _M.incr("batch_seals", len(sealable))
            for lane, comp in zip(sealable, comps or [None] * len(sealable)):
                self._seal_locked(lane, on_seal, comp=comp)
        self.drain_seals()

    # --------------------------------------------------------- async sealing

    def enable_async_seals(self) -> None:
        """Move rollover compression off the appending thread onto a single
        seal worker (the write pipeline's commit stage must not stall on an
        unlucky 32 MiB compress).  Idempotent.  Durability is unchanged:
        the raw file persists (and serves reads) until the worker's sealed
        file is in place, exactly the ordering ``seal`` already guarantees
        for concurrent readers."""
        if self._seal_q is not None:
            return
        self._seal_q = queue.Queue()
        self._seal_thread = threading.Thread(
            target=self._seal_worker, name="container-seal", daemon=True)
        self._seal_thread.start()

    def _seal_worker(self) -> None:
        while True:
            item = self._seal_q.get()
            if item is None:
                self._seal_q.task_done()
                return
            cid, payload, had_raw, on_seal, comp = item
            try:
                self.seal(cid, data=payload, have_raw=had_raw, comp=comp)
                if on_seal is not None:
                    on_seal(cid)
            except BaseException as e:  # noqa: BLE001 — re-raised at drain
                self._seal_exc = e
            finally:
                self._seal_q.task_done()

    def drain_seals(self) -> None:
        """Barrier: every enqueued async seal is on disk (or its error is
        raised here).  No-op with async seals disabled."""
        if self._seal_q is None:
            return
        self._seal_q.join()
        if self._seal_exc is not None:
            exc, self._seal_exc = self._seal_exc, None
            raise exc

    def close_async_seals(self) -> None:
        """Drain, then stop the seal worker (shutdown hook)."""
        if self._seal_q is None:
            return
        self.drain_seals()
        self._seal_q.put(None)
        self._seal_thread.join()
        self._seal_q = None
        self._seal_thread = None

    # -------------------------------------------------------------- reading

    def _cache_probe(self, cid: int) -> bytes | None:
        with self._cache_lock:
            if cid in self._cache:
                _M.incr("cache_hit")
                # true LRU: re-insert on hit so eviction drops the least
                # RECENTLY used container, not the oldest insertion (FIFO
                # evicted the hottest container under cyclic read sets)
                data = self._cache.pop(cid)
                self._cache[cid] = data
                _gauge_hit_ratio()
                return data
            _M.incr("cache_miss")
        _gauge_hit_ratio()
        return None

    def _read_undecoded(self, cid: int) -> bytes | None:
        """Open-lane memory image or raw-file bytes — the no-decompress
        sources; None when the container is sealed (or gone)."""
        from hdrf_tpu.reduction import accounting  # storage->reduction: leaf-only

        for lane in self._lanes:
            with lane.lock:
                if lane.container_id == cid and lane.image is not None:
                    accounting.record_container_decode(len(lane.image))
                    return bytes(lane.image)  # open lane: serve from memory
        try:
            # Still-open container: read raw bytes directly
            # (DataConstructor.java:482-490's skip-decompress path).  Open
            # without an exists() pre-check: a concurrent seal unlinks the raw
            # file only *after* the sealed file is in place, so on ENOENT the
            # sealed path below is guaranteed readable.
            with open(self._raw_path(cid), "rb") as f:
                magic = _SEAL_HDR.unpack(f.read(_SEAL_HDR.size))[0]
                if magic != _RAW_MAGIC:
                    raise IOError(f"container {cid}: bad raw magic {magic:#x}")
                data = f.read()
                accounting.record_container_decode(len(data))
                return data
        except FileNotFoundError:
            return None

    def _sealed_parse(self, cid: int) -> tuple[str, int, bytes]:
        """(codec name, uncompressed size, compressed payload) of the
        sealed container — the decode deferred so the read coalescer can
        run a whole window's payloads through one batched dispatch."""
        try:
            with open(self._sealed_path(cid), "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            # Demoted to the EC cold tier: the sealed file was replaced by
            # k+m stripes.  The DN-installed fallback reassembles the exact
            # sealed-file bytes from any k survivors (degraded read path).
            if self._stripe_fallback is None:
                raise
            blob = self._stripe_fallback(cid)
            if blob is None:
                raise
        magic, usize, codec_id = _SEAL_HDR.unpack(blob[:_SEAL_HDR.size])
        if magic != _SEAL_MAGIC:
            raise IOError(f"container {cid}: bad magic {magic:#x}")
        return codecs.CODEC_NAMES[codec_id], usize, blob[_SEAL_HDR.size:]

    def _cache_insert(self, cid: int, data: bytes) -> None:
        with self._cache_lock:
            self._cache.pop(cid, None)  # keep the re-insert most-recent
            self._cache[cid] = data
            while len(self._cache) > self._cache_cap:
                self._cache.pop(next(iter(self._cache)))
                _M.incr("cache_evict")

    def read_container(self, cid: int) -> bytes:
        """Full uncompressed container bytes (open or sealed)."""
        data = self._cache_probe(cid)
        if data is not None:
            return data
        data = self._read_undecoded(cid)
        if data is not None:
            return data
        codec_name, usize, payload = self._sealed_parse(cid)
        data = codecs.decompress(codec_name, payload, usize)
        from hdrf_tpu.reduction import accounting

        accounting.record_container_decode(len(data))
        self._cache_insert(cid, data)
        return data

    def read_containers(self, cids: list[int],
                        decompress_batch=None) -> dict[int, bytes]:
        """Grouped form of ``read_container``: every distinct cid resolved
        once, and the sealed payloads that actually need decompression run
        through ONE ``decompress_batch(codec_names, blobs, usizes)`` call
        (the read coalescer passes ops/dispatch.block_decompress_batch) —
        the read-side sibling of flush_open's compress_batch_fn grouping.
        LRU probes, open/raw fast paths and decode accounting are
        identical to the per-cid path."""
        out: dict[int, bytes] = {}
        pending: list[tuple[int, str, int, bytes]] = []
        for cid in dict.fromkeys(cids):
            data = self._cache_probe(cid)
            if data is None:
                data = self._read_undecoded(cid)
            if data is not None:
                out[cid] = data
                continue
            codec_name, usize, payload = self._sealed_parse(cid)
            pending.append((cid, codec_name, usize, payload))
        if pending:
            if decompress_batch is not None:
                datas = decompress_batch([p[1] for p in pending],
                                         [p[3] for p in pending],
                                         [p[2] for p in pending])
            else:
                datas = [codecs.decompress(c, b, u)
                         for _, c, u, b in pending]
            from hdrf_tpu.reduction import accounting

            for (cid, _c, _u, _b), data in zip(pending, datas):
                accounting.record_container_decode(len(data))
                self._cache_insert(cid, data)
                out[cid] = data
        return out

    def read_chunks(self, locs: list[tuple[int, int, int]]) -> list[bytes]:
        """Fetch many chunks, grouping by container so each container is read
        and decompressed once (quickBuildMT's grouping,
        DataConstructor.java:375-395)."""
        by_cid: dict[int, list[int]] = {}
        for i, (cid, _, _) in enumerate(locs):
            by_cid.setdefault(cid, []).append(i)
        out: list[bytes | None] = [None] * len(locs)
        for cid, idxs in by_cid.items():
            data = self.read_container(cid)
            for i in idxs:
                _, off, ln = locs[i]
                out[i] = data[off:off + ln]
        return out  # type: ignore[return-value]

    # ----------------------------------------------------------- compaction

    def copy_live(self, cid: int, live: dict[bytes, tuple[int, int]],
                  on_seal=None) -> dict[bytes, tuple[int, int, int]]:
        """Copy a container's *live* chunks into the current open lane.
        ``live`` maps fingerprint -> (offset, len) within ``cid``.  Returns
        fingerprint -> new (cid, off, len).

        Compaction protocol (crash-safe ordering): ``copy_live`` (bytes
        durable in new container) -> ``ChunkIndex.record_moves`` (index commit)
        -> ``delete_container(cid)``.  A crash before the index commit leaves
        only orphan copies; the old container is deleted strictly after the
        index stops referencing it."""
        data = self.read_container(cid)
        hashes = list(live.keys())
        chunks = [data[off:off + ln] for off, ln in (live[h] for h in hashes)]
        new_locs = self.append_chunks(chunks, on_seal=on_seal)
        return dict(zip(hashes, new_locs))

    def sealed_file_bytes(self, cid: int) -> bytes | None:
        """Raw sealed FILE bytes (header + compressed payload) — the EC
        cold tier's striping unit (stripe_store.py encodes exactly these
        bytes, so reassembly needs no re-compression).  None when the
        container is open or already striped."""
        try:
            with open(self._sealed_path(cid), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def drop_sealed_file(self, cid: int) -> int:
        """Unlink just the sealed file (EC demotion: the stripes + manifest
        now carry the bytes).  Unlike delete_container this keeps the LRU
        entry (the decompressed payload is still valid) and does NOT fire
        ``_on_delete`` — the container remains logically present.  Returns
        bytes freed."""
        path = self._sealed_path(cid)
        try:
            size = os.path.getsize(path)
            os.unlink(path)
            return size
        except OSError:
            return 0

    def quarantine(self, cid: int) -> int:
        """Rename the container's files aside (``.quar`` suffix) so it can
        never be served again — a scrub-confirmed corrupt container must
        not satisfy another read, across restarts included
        (markBlockAsCorrupt's never-serve guarantee applied to the shared
        container).  A rename, not an unlink: the corrupt bytes stay on
        disk for forensics and are censused as
        ``garbage_bytes|class=quarantined`` until GC reclaims them.  Does
        NOT fire ``_on_delete`` (the container remains logically present;
        re-replication restores its blocks elsewhere).  Returns bytes
        quarantined."""
        moved = 0
        for p in (self._raw_path(cid), self._sealed_path(cid)):
            try:
                size = os.path.getsize(p)
                os.rename(p, p + ".quar")
                moved += size
            except OSError:
                continue
        with self._cache_lock:
            self._cache.pop(cid, None)
        if self._on_retire is not None:
            self._on_retire(cid)
        return moved

    def delete_container(self, cid: int) -> None:
        for p in (self._raw_path(cid), self._sealed_path(cid)):
            if os.path.exists(p):
                os.unlink(p)
        with self._cache_lock:
            self._cache.pop(cid, None)
        if self._on_retire is not None:
            self._on_retire(cid)
        if self._on_delete is not None:
            self._on_delete(cid)

    def has_container(self, cid: int, need_bytes: int = 0) -> bool:
        """True if the container's bytes are reachable AND cover at least
        ``need_bytes`` of payload.  The extent check matters: the typical
        fsync_containers=False crash artifact is a TRUNCATED raw file (the
        un-fsync'd tail lost to writeback), not a missing one.  Sources:
        an open lane's memory image, the raw file (size minus header), or
        the sealed file (uncompressed size from its fsync'd header)."""
        for lane in self._lanes:
            with lane.lock:
                if lane.container_id == cid and lane.image is not None:
                    return len(lane.image) >= need_bytes
        try:
            sz = os.path.getsize(self._raw_path(cid))
            return sz - _SEAL_HDR.size >= need_bytes
        except OSError:
            pass
        try:
            with open(self._sealed_path(cid), "rb") as f:
                hdr = f.read(_SEAL_HDR.size)
                if len(hdr) < _SEAL_HDR.size:
                    return False
                magic, usize, _codec = _SEAL_HDR.unpack(hdr)
                return magic == _SEAL_MAGIC and usize >= need_bytes
        except OSError:
            pass
        if self._stripe_probe is not None:
            usize = self._stripe_probe(cid)
            if usize is not None:  # striped: manifest records payload size
                return usize >= need_bytes
        return False

    def container_ids(self) -> list[int]:
        ids = set()
        for name in os.listdir(self._dir):
            stem = name.split(".")[0]
            if stem.isdigit() and (name.endswith(".raw") or name.endswith(".sealed")):
                ids.add(int(stem))
        return sorted(ids)

    def physical_bytes(self) -> int:
        total = 0
        for name in os.listdir(self._dir):
            if name.endswith(".raw") or name.endswith(".sealed"):
                total += os.path.getsize(os.path.join(self._dir, name))
        return total

    def container_sizes(self) -> dict[int, int]:
        """cid -> bytes on disk (raw + sealed forms summed) — the
        denominator of the utilization accounting
        (reduction/accounting.py:utilization_hist).  stat() calls only;
        never opens the files."""
        out: dict[int, int] = {}
        for name in os.listdir(self._dir):
            stem = name.split(".")[0]
            if stem.isdigit() and (name.endswith(".raw")
                                   or name.endswith(".sealed")):
                cid = int(stem)
                out[cid] = out.get(cid, 0) + os.path.getsize(
                    os.path.join(self._dir, name))
        return out
