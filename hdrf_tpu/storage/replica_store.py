"""Replica store: block lifecycle with first-class logical/physical lengths.

Equivalent of the reference's ``FsDatasetImpl.java`` (replica files, RBW ->
finalized lifecycle, `FsDatasetImpl.finalizeBlock`) — but designed so reduced
blocks need **no shadow-length patches**.  The reference leaves the replica
file at 0 bytes when a block is reduced and patches ~12 length/consistency
checks across HDFS to tolerate it (SURVEY.md §2.3: the getLength Redis
probe FsDatasetImpl.java:735-761, `DirectoryScanner` check disabled :437-438,
`Replica.setNumBytes` spoofing, ...).

Here every replica carries a sidecar ``BlockMeta`` record from creation:

- ``logical_len``  — bytes the client wrote (what reads/reports expose)
- ``physical_len`` — bytes on local disk for THIS replica's data file
  (0 for dedup'd blocks whose bytes live in chunk containers)
- ``scheme``       — which ReductionScheme produced the stored form

``length()`` returns the logical length by construction; the scanner verifies
the *physical* file against ``physical_len`` — so the reference's
"0-byte-file-means-corrupt" false positive cannot occur.

Layout under the volume root::

    rbw/blk_<id>           in-flight replica data (may stay empty for dedup)
    finalized/blk_<id>       finalized data file (direct & compress schemes)
    finalized/blk_<id>.meta  msgpack BlockMeta + packet CRCs (meta file analog)
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field

import msgpack

from hdrf_tpu.utils import fault_injection, metrics

_M = metrics.registry("replica_store")

RBW = "rbw"
FINALIZED = "finalized"


@dataclass
class BlockMeta:
    block_id: int
    gen_stamp: int
    logical_len: int
    physical_len: int
    scheme: str  # reduction scheme name ("direct", "lz4", "dedup_lz4", ...)
    # crc32c per checksum_chunk bytes of the *logical* data
    # (BlockReceiver writes checksums even in reduction mode, :924-986).
    checksum_chunk: int = 64 * 1024
    checksums: list[int] = field(default_factory=list)

    def pack(self) -> bytes:
        return msgpack.packb([self.block_id, self.gen_stamp, self.logical_len,
                              self.physical_len, self.scheme, self.checksum_chunk,
                              self.checksums])

    @staticmethod
    def unpack(data: bytes) -> "BlockMeta":
        b, g, ll, pl, s, cc, cs = msgpack.unpackb(data, raw=False)
        return BlockMeta(b, g, ll, pl, s, cc, list(cs))


class ReplicaWriter:
    """An in-flight (RBW) replica.  Data may be streamed for direct/compress
    schemes; dedup'd blocks finalize with an empty data file by design."""

    def __init__(self, store: "ReplicaStore", block_id: int, gen_stamp: int):
        self._store = store
        self.block_id = block_id
        self.gen_stamp = gen_stamp
        self._path = store._path(RBW, block_id)
        self._fh = open(self._path, "wb")
        self._written = 0

    def write(self, data: bytes) -> None:
        self._fh.write(data)
        self._written += len(data)

    @property
    def bytes_written(self) -> int:
        return self._written

    def flush_visible(self, checksums: list[int],
                      checksum_chunk: int = 64 * 1024,
                      sync: bool = False) -> None:
        """hflush/hsync support (DFSOutputStream.java:573/:580): expose the
        bytes written so far to concurrent readers — the reference's RBW
        visible length (ReplicaInPipeline.setBytesAcked).  ``sync`` also
        fsyncs data + sidecar so the prefix survives a DataNode crash
        (the replica is then promoted to finalized on restart)."""
        self._fh.flush()
        if sync:
            os.fsync(self._fh.fileno())
        meta = BlockMeta(self.block_id, self.gen_stamp, self._written,
                         self._written, "direct", checksum_chunk,
                         list(checksums))
        mp = self._path + ".meta"
        with open(mp + ".tmp", "wb") as f:
            f.write(meta.pack())
            if sync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(mp + ".tmp", mp)
        self._store._set_visible(meta)
        _M.incr("hsyncs" if sync else "hflushes")

    def finalize(self, logical_len: int, scheme: str,
                 checksums: list[int] | None = None,
                 checksum_chunk: int = 64 * 1024) -> BlockMeta:
        """Move RBW -> finalized with authoritative metadata
        (FsDatasetImpl.finalizeBlock analog, invoked from
        BlockReceiver.java:1816)."""
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._fh.close()
        fault_injection.point("replica.finalize", block_id=self.block_id)
        meta = BlockMeta(self.block_id, self.gen_stamp, logical_len,
                         self._written, scheme, checksum_chunk, checksums or [])
        dst = self._store._path(FINALIZED, self.block_id)
        os.replace(self._path, dst)
        if os.path.exists(self._path + ".meta"):
            os.unlink(self._path + ".meta")  # rbw-visible sidecar superseded
        # write-replace, never open("wb") the existing meta: on a supersede
        # rewrite (append/recovery finalize) the old meta may be hardlinked
        # into an upgrade snapshot (storage/version.py), and truncating the
        # shared inode would corrupt the rollback image
        with open(dst + ".meta.tmp", "wb") as f:
            f.write(meta.pack())
            f.flush()
            os.fsync(f.fileno())
        os.replace(dst + ".meta.tmp", dst + ".meta")
        self._store._register(meta)
        _M.incr("finalized")
        return meta

    def abort(self) -> None:
        self._fh.close()
        for p in (self._path, self._path + ".meta"):
            if os.path.exists(p):
                os.unlink(p)
        self._store._release_rbw(self.block_id)

    def detach(self) -> None:
        """Close WITHOUT deleting — the crash-simulation teardown: a dead
        process leaves its rbw file and hflush sidecar on disk exactly as
        they were, which is what restart promotion recovers from."""
        self._fh.close()
        self._store._release_rbw(self.block_id)


class ReplicaStore:
    def __init__(self, directory: str):
        self._dir = directory
        for sub in (RBW, FINALIZED):
            os.makedirs(os.path.join(directory, sub), exist_ok=True)
        self._lock = threading.Lock()
        self._replicas: dict[int, BlockMeta] = {}
        self._rbw: set[int] = set()  # block ids with an open writer
        # hflush'd in-flight replicas: block_id -> meta with the VISIBLE
        # length (bytes a concurrent reader may see; ReplicaInPipeline
        # .getVisibleLength analog)
        self._visible: dict[int, BlockMeta] = {}
        self._recover()

    def _path(self, state: str, block_id: int) -> str:
        return os.path.join(self._dir, state, f"blk_{block_id}")

    def _recover(self) -> None:
        """Load finalized replicas from disk; RBW files with an hflush
        sidecar are PROMOTED to finalized at their last synced visible
        length (the reference recovers RBW as RWR with its on-disk bytes,
        FsDatasetImpl.recoverRbw); orphaned RBW files without one are
        dropped (crash mid-write — pipeline recovery re-writes the block)."""
        fdir = os.path.join(self._dir, FINALIZED)
        for name in os.listdir(fdir):
            if name.endswith(".meta"):
                with open(os.path.join(fdir, name), "rb") as f:
                    meta = BlockMeta.unpack(f.read())
                self._replicas[meta.block_id] = meta
        rdir = os.path.join(self._dir, RBW)
        for name in os.listdir(rdir):
            p = os.path.join(rdir, name)
            if name.endswith(".meta") or name.endswith(".tmp"):
                continue
            mp = p + ".meta"
            if not os.path.exists(mp):
                os.unlink(p)
                continue
            try:
                with open(mp, "rb") as f:
                    meta = BlockMeta.unpack(f.read())
            except Exception:  # noqa: BLE001 — torn sidecar (power loss
                # between rename and data reaching disk): drop the pair
                # rather than crash-loop the DataNode on startup
                os.unlink(p)
                os.unlink(mp)
                _M.incr("rbw_sidecar_torn")
                continue
            if meta.block_id in self._replicas:   # superseded already
                os.unlink(p)
                os.unlink(mp)
                continue
            # cut unsynced bytes beyond the last visible length, then
            # finalize in place
            size = os.path.getsize(p)
            if size > meta.physical_len:
                with open(p, "r+b") as f:
                    f.truncate(meta.physical_len)
            elif size < meta.physical_len:
                # crash lost the tail of a non-synced flush: keep the bytes
                # that ARE there, re-deriving the final chunk's checksum
                meta.logical_len = meta.physical_len = size
                nchunks = -(-size // meta.checksum_chunk) if size else 0
                del meta.checksums[nchunks:]
                if meta.checksums:
                    from hdrf_tpu import native
                    with open(p, "rb") as f:
                        f.seek((nchunks - 1) * meta.checksum_chunk)
                        meta.checksums[-1] = native.crc32c(f.read())
                with open(mp, "wb") as f:
                    f.write(meta.pack())
            dst = self._path(FINALIZED, meta.block_id)
            os.replace(p, dst)
            os.replace(mp, dst + ".meta")
            self._replicas[meta.block_id] = meta
            _M.incr("rbw_promoted")
        for name in os.listdir(rdir):             # leftover sidecars/tmps
            os.unlink(os.path.join(rdir, name))

    # -------------------------------------------------------------- lifecycle

    def create_rbw(self, block_id: int, gen_stamp: int = 0,
                   storage_type: str | None = None) -> ReplicaWriter:
        # ``storage_type`` is a volume-routing hint consumed by VolumeSet
        # (storage/volumes.py); a single store has nowhere to route.
        with self._lock:
            existing = self._replicas.get(block_id)
            if existing is not None and gen_stamp <= existing.gen_stamp:
                raise FileExistsError(f"block {block_id} already finalized")
            # gen_stamp > existing: a supersede rewrite (append / recovery) —
            # the old replica keeps serving reads until finalize replaces it
            # atomically (the RBW writes to the rbw/ path, os.replace swaps)
            if block_id in self._rbw:
                raise FileExistsError(f"block {block_id} already being written")
            self._rbw.add(block_id)
        try:
            return ReplicaWriter(self, block_id, gen_stamp)
        except Exception:
            with self._lock:
                self._rbw.discard(block_id)
            raise

    def _register(self, meta: BlockMeta) -> None:
        with self._lock:
            self._replicas[meta.block_id] = meta
            self._rbw.discard(meta.block_id)
            self._visible.pop(meta.block_id, None)

    def _release_rbw(self, block_id: int) -> None:
        with self._lock:
            self._rbw.discard(block_id)
            self._visible.pop(block_id, None)

    def _set_visible(self, meta: BlockMeta) -> None:
        with self._lock:
            self._visible[meta.block_id] = meta

    def get_meta(self, block_id: int) -> BlockMeta | None:
        """Finalized meta, or the hflush'd visible meta for a block still
        in the write pipeline — which is what lets a concurrent reader see
        flushed bytes (BlockSender serves through this)."""
        with self._lock:
            return (self._replicas.get(block_id)
                    or self._visible.get(block_id))

    def is_rbw(self, block_id: int) -> bool:
        """An open in-flight writer exists (replica-being-written): block
        recovery must not conclude "no replica" while the pipeline is still
        alive or its teardown persist is in progress."""
        with self._lock:
            return block_id in self._rbw

    def length(self, block_id: int) -> int:
        """Logical length — authoritative from metadata, never from file size.
        Replaces the patched `FsDatasetImpl.getLength` (:735-761)."""
        meta = self.get_meta(block_id)
        if meta is None:
            raise KeyError(f"block {block_id} not found")
        return meta.logical_len

    def read_data(self, block_id: int, offset: int = 0, length: int = -1) -> bytes:
        """Raw stored bytes of the replica data file (post-reduction form).
        An hflush'd in-flight replica serves its VISIBLE prefix from the
        rbw file (clamped — the writer may be ahead of the last flush)."""
        with self._lock:
            vis = (None if block_id in self._replicas
                   else self._visible.get(block_id))
        if vis is not None:
            end = vis.physical_len if length < 0 \
                else min(offset + length, vis.physical_len)
            try:
                with open(self._path(RBW, block_id), "rb") as f:
                    f.seek(offset)
                    return f.read(max(end - offset, 0))
            except FileNotFoundError:
                pass  # finalize() raced us (os.replace happens before the
                # meta registers): the finalized file below has the bytes
        p = self._path(FINALIZED, block_id)
        with open(p, "rb") as f:
            f.seek(offset)
            return f.read() if length < 0 else f.read(length)

    def data_path(self, block_id: int) -> str:
        return self._path(FINALIZED, block_id)

    def truncate_replica(self, block_id: int, new_len: int,
                         new_gs: int | None = None) -> bool:
        """Cut a DIRECT replica to ``new_len`` logical bytes (the
        BlockRecoveryWorker length-sync truncation).  Reduced replicas are
        all-or-nothing — a committed reduced block never has a divergent
        length, so only equal-length no-ops are legal there.  ``new_gs``
        restamps the replica with the recovery generation stamp (the
        commitBlockSynchronization restamp: without it the next full block
        report would present the old generation and the NN would invalidate
        the just-recovered replica)."""
        with self._lock:
            meta = self._replicas.get(block_id)
            if meta is None:
                return False
            if meta.logical_len <= new_len and \
                    (new_gs is None or new_gs <= meta.gen_stamp):
                return True  # nothing to cut or restamp (recovery retry)
            if meta.logical_len > new_len:
                if meta.scheme != "direct":
                    raise IOError(f"block {block_id}: cannot truncate a "
                                  f"{meta.scheme} replica to {new_len}")
                p = self._path(FINALIZED, block_id)
                # write-replace, never truncate in place: finalized data
                # files are hardlinked into upgrade snapshots
                # (storage/version.py _snapshot), so an in-place mutation
                # would silently corrupt the rollback image
                with open(p, "rb") as f:
                    kept = f.read(new_len)
                with open(p + ".tmp", "wb") as f:
                    f.write(kept)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(p + ".tmp", p)
                nchunks = -(-new_len // meta.checksum_chunk) if new_len else 0
                meta.logical_len = meta.physical_len = new_len
                del meta.checksums[nchunks:]
                if new_len % meta.checksum_chunk and meta.checksums:
                    # re-derive the now-partial final chunk's checksum
                    with open(p, "rb") as f:
                        f.seek((nchunks - 1) * meta.checksum_chunk)
                        from hdrf_tpu import native
                        meta.checksums[-1] = native.crc32c(f.read())
                _M.incr("replicas_truncated")
            if new_gs is not None and new_gs > meta.gen_stamp:
                meta.gen_stamp = new_gs
            mp = self._path(FINALIZED, block_id) + ".meta"
            with open(mp + ".tmp", "wb") as f:
                f.write(meta.pack())
                f.flush()
                os.fsync(f.fileno())
            os.replace(mp + ".tmp", mp)  # write-replace: see above
            return True

    def adopt(self, meta: BlockMeta, data: bytes) -> None:
        """Install a finalized replica wholesale (intra-DN volume move,
        DiskBalancer's movePhysicalBlock analog): data + meta land under
        finalized/ via write-then-rename, then register."""
        dst = self._path(FINALIZED, meta.block_id)
        with open(dst + ".tmp", "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(dst + ".tmp", dst)
        with open(dst + ".meta.tmp", "wb") as f:
            f.write(meta.pack())
            f.flush()
            os.fsync(f.fileno())
        os.replace(dst + ".meta.tmp", dst + ".meta")
        self._register(meta)
        _M.incr("replicas_adopted")

    def delete(self, block_id: int) -> None:
        with self._lock:
            self._replicas.pop(block_id, None)
        for p in (self._path(FINALIZED, block_id),
                  self._path(FINALIZED, block_id) + ".meta"):
            if os.path.exists(p):
                os.unlink(p)
        _M.incr("deleted")

    def block_ids(self) -> list[int]:
        """Block report source (BlockListAsLongs analog)."""
        with self._lock:
            return sorted(self._replicas)

    def block_report(self) -> list[tuple[int, int, int]]:
        """(block_id, gen_stamp, logical_len) triples — lengths are real, not
        the reference's spoofed `setNumBytes` values (BlockListAsLongs.java:547-554)."""
        with self._lock:
            return [(m.block_id, m.gen_stamp, m.logical_len)
                    for m in self._replicas.values()]

    # ---------------------------------------------------------------- scanner

    def scan(self) -> list[str]:
        """DirectoryScanner analog: reconcile memory vs disk.  Because
        physical_len is first-class, a 0-byte data file for a dedup'd block is
        *consistent*, not corrupt (vs DirectoryScanner.java:437-438 which the
        reference had to disable)."""
        problems: list[str] = []
        with self._lock:
            replicas = dict(self._replicas)
        fdir = os.path.join(self._dir, FINALIZED)
        on_disk = {int(n[4:]) for n in os.listdir(fdir)
                   if n.startswith("blk_") and not n.endswith(".meta")}
        for bid, meta in replicas.items():
            if bid not in on_disk:
                problems.append(f"block {bid}: data file missing")
                continue
            size = os.path.getsize(self._path(FINALIZED, bid))
            if size != meta.physical_len:
                problems.append(
                    f"block {bid}: physical length {size} != meta {meta.physical_len}")
        for bid in on_disk - set(replicas):
            problems.append(f"block {bid}: orphan data file (no meta)")
        _M.incr("scans")
        return problems

    def physical_bytes(self) -> int:
        with self._lock:
            return sum(m.physical_len for m in self._replicas.values())
