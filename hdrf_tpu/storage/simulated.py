"""Simulated replica store: in-memory FsDataset for protocol tests at scale.

The SimulatedFSDataset analog (server/datanode/SimulatedFSDataset.java:91,
1.5 kLoC in the reference): implements the ReplicaStore surface with bytes in
RAM — no disk I/O — so NameNode-logic and wire-protocol tests can run
thousands of blocks per DN cheaply.  Enabled via
``DataNodeConfig.simulated_dataset`` (the reference injects it with
SimulatedFSDataset.setFactory).
"""

from __future__ import annotations

import threading

from hdrf_tpu.storage.replica_store import BlockMeta
from hdrf_tpu.utils import metrics

_M = metrics.registry("simulated_dataset")


class SimulatedWriter:
    def __init__(self, store: "SimulatedReplicaStore", block_id: int,
                 gen_stamp: int):
        self._store = store
        self._block_id = block_id
        self._gen_stamp = gen_stamp
        self._parts: list[bytes] = []

    def write(self, data: bytes) -> None:
        self._parts.append(bytes(data))

    @property
    def bytes_written(self) -> int:
        return sum(len(p) for p in self._parts)

    def finalize(self, logical_len: int, scheme: str, checksums: list[int],
                 checksum_chunk: int) -> BlockMeta:
        data = b"".join(self._parts)
        meta = BlockMeta(block_id=self._block_id, gen_stamp=self._gen_stamp,
                         logical_len=logical_len, physical_len=len(data),
                         scheme=scheme, checksums=list(checksums),
                         checksum_chunk=checksum_chunk)
        with self._store._lock:
            self._store._data[self._block_id] = data
            self._store._meta[self._block_id] = meta
            self._store._rbw.discard(self._block_id)
        _M.incr("blocks_finalized")
        return meta

    def abort(self) -> None:
        with self._store._lock:
            self._store._rbw.discard(self._block_id)


class SimulatedReplicaStore:
    """Drop-in for storage.replica_store.ReplicaStore, RAM-backed."""

    def __init__(self, directory: str = ""):
        self._lock = threading.Lock()
        self._data: dict[int, bytes] = {}
        self._meta: dict[int, BlockMeta] = {}
        self._rbw: set[int] = set()

    def create_rbw(self, block_id: int, gen_stamp: int = 0,
                   storage_type: str | None = None) -> SimulatedWriter:
        with self._lock:
            # same contract as the real store: finalized OR in-flight
            # duplicates are rejected
            if block_id in self._rbw or block_id in self._meta:
                raise FileExistsError(f"block {block_id} already exists")
            self._rbw.add(block_id)
        return SimulatedWriter(self, block_id, gen_stamp)

    def get_meta(self, block_id: int) -> BlockMeta | None:
        return self._meta.get(block_id)

    def is_rbw(self, block_id: int) -> bool:
        with self._lock:
            return block_id in self._rbw

    def length(self, block_id: int) -> int:
        return self._meta[block_id].logical_len  # KeyError like the real store

    def read_data(self, block_id: int, offset: int = 0,
                  length: int = -1) -> bytes:
        if block_id not in self._data:  # FileNotFoundError like the real store
            raise FileNotFoundError(f"no replica data for block {block_id}")
        data = self._data[block_id]
        end = len(data) if length < 0 else min(offset + length, len(data))
        return data[offset:end]

    def data_path(self, block_id: int) -> str:
        raise OSError("simulated dataset has no on-disk paths "
                      "(short-circuit reads are disabled)")

    def truncate_replica(self, block_id: int, new_len: int,
                         new_gs: int | None = None) -> bool:
        """Length-sync truncation + recovery restamp (same contract as
        ReplicaStore.truncate_replica)."""
        with self._lock:
            meta = self._meta.get(block_id)
            if meta is None:
                return False
            if meta.logical_len > new_len:
                if meta.scheme != "direct":
                    raise IOError(f"block {block_id}: cannot truncate a "
                                  f"{meta.scheme} replica to {new_len}")
                self._data[block_id] = self._data[block_id][:new_len]
                nchunks = -(-new_len // meta.checksum_chunk) if new_len else 0
                meta.logical_len = meta.physical_len = new_len
                del meta.checksums[nchunks:]
                if new_len % meta.checksum_chunk and meta.checksums:
                    from hdrf_tpu import native
                    meta.checksums[-1] = native.crc32c(
                        self._data[block_id][(nchunks - 1)
                                             * meta.checksum_chunk:])
            if new_gs is not None and new_gs > meta.gen_stamp:
                meta.gen_stamp = new_gs
            return True

    def delete(self, block_id: int) -> None:
        with self._lock:
            self._data.pop(block_id, None)
            self._meta.pop(block_id, None)

    def block_ids(self) -> list[int]:
        return list(self._meta)

    def block_report(self) -> list[tuple[int, int, int]]:
        return [(m.block_id, m.gen_stamp, m.logical_len)
                for m in self._meta.values()]

    def scan(self) -> list[str]:
        return []  # nothing on disk to reconcile

    def physical_bytes(self) -> int:
        return sum(len(d) for d in self._data.values())
