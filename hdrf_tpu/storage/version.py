"""Storage layout versioning + upgrade/rollback (Storage.java analog).

Re-expresses the reference's storage-directory versioning —
``server/common/Storage.java:77`` (VERSION files, layout checks,
upgrade/rollback state machine) and ``BlockPoolSliceStorage``'s
rolling-upgrade trash — for every hdrf_tpu store directory (NameNode meta
dir, DataNode data dir, JournalNode dir):

- Every store dir carries a ``VERSION`` file (``layoutVersion``,
  ``storageType``, ``ctime``) written at creation and checked on load.
- A dir with an OLDER layout is upgraded in place THROUGH a snapshot: the
  current tree is first preserved under ``previous/`` (hardlinks for
  immutable files, copies for mutable ones — the reference's
  doUpgrade hardlink trick), then registered upgraders run one layout step
  at a time, then VERSION is bumped.  A crash mid-upgrade leaves
  ``previous.tmp/`` behind; the next load discards it and re-runs the
  upgrade from the intact current tree.
- ``rollback()`` restores the pre-upgrade tree byte-exactly from
  ``previous/`` (NameNode -rollback analog); ``finalize_upgrade()`` drops
  the snapshot (dfsadmin -finalizeUpgrade).
- A dir with a NEWER layout than this binary refuses to load (the
  reference's "future layout version" IncorrectVersionException) — running
  old code over a new format is how stores get bricked.

Layout history:

- datanode 1: flat ``replicas/ containers/ index/`` under the data dir.
- datanode 2: per-volume roots ``volumes/vol-0/{replicas,containers}``
  (multi-volume DataNodes; the chunk index stays DN-wide at ``index/``).
- namenode 1 / journal 1: initial versioned layouts (the VERSION file
  itself is what the bump from implicit 0 adds).
"""

from __future__ import annotations

import os
import shutil
import time

VERSION_FILE = "VERSION"
PREVIOUS = "previous"
PREVIOUS_TMP = "previous.tmp"
# Present while an upgrade is running (created before the snapshot renames
# into place, removed after the last upgrader + VERSION bump).  Lets a
# restart distinguish a TORN upgrade (flag + previous/ -> auto-rollback and
# retry) from a COMPLETED one awaiting finalize (previous/ without flag ->
# a new upgrade must refuse until finalized, or it would overwrite the
# operator's rollback image with a partially-newer tree).
UPGRADE_FLAG = "upgrade.inprogress"

CURRENT = {"datanode": 2, "namenode": 1, "journal": 1}

# Basenames that are immutable once written (snapshot may hardlink them;
# every mutation path for these writes a NEW file + rename, never in
# place): finalized replica data/meta and sealed containers.
_IMMUTABLE_PREFIXES = ("blk_",)
_IMMUTABLE_SUFFIXES = (".sealed",)


class LayoutError(Exception):
    pass


def read_version(directory: str) -> dict | None:
    p = os.path.join(directory, VERSION_FILE)
    try:
        with open(p, "r", encoding="utf-8") as f:
            out: dict = {}
            for line in f:
                line = line.strip()
                if line and "=" in line:
                    k, v = line.split("=", 1)
                    out[k] = v
            out["layoutVersion"] = int(out.get("layoutVersion", 0))
            return out
    except FileNotFoundError:
        return None


def write_version(directory: str, kind: str, layout: int) -> None:
    tmp = os.path.join(directory, VERSION_FILE + ".tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(f"layoutVersion={layout}\n"
                f"storageType={kind}\n"
                f"ctime={int(time.time())}\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(directory, VERSION_FILE))


def _is_immutable(name: str) -> bool:
    return (name.startswith(_IMMUTABLE_PREFIXES)
            and not name.endswith(".tmp")) \
        or name.endswith(_IMMUTABLE_SUFFIXES)


def _snapshot(directory: str) -> None:
    """Preserve the current tree under previous/ (crash-safe: built as
    previous.tmp, renamed when complete)."""
    tmp = os.path.join(directory, PREVIOUS_TMP)
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    for root, dirs, files in os.walk(directory):
        rel = os.path.relpath(root, directory)
        parts = rel.split(os.sep)
        if parts[0] in (PREVIOUS, PREVIOUS_TMP):
            dirs[:] = []
            continue
        dst_root = os.path.join(tmp, rel) if rel != "." else tmp
        os.makedirs(dst_root, exist_ok=True)
        for name in files:
            if rel == "." and name == UPGRADE_FLAG:
                continue   # transient marker, never part of the image
            src = os.path.join(root, name)
            dst = os.path.join(dst_root, name)
            if _is_immutable(name):
                os.link(src, dst)        # doUpgrade hardlink trick
            else:
                shutil.copy2(src, dst)
    os.replace(tmp, os.path.join(directory, PREVIOUS))


def ensure_layout(directory: str, kind: str, upgraders=None) -> int:
    """Check/create/upgrade ``directory`` to the current layout for
    ``kind``.  ``upgraders`` maps from-layout -> fn(directory) applying
    one layout step.  Returns the layout the dir now has."""
    current = CURRENT[kind]
    os.makedirs(directory, exist_ok=True)
    # discard a torn mid-SNAPSHOT tree; the current tree is intact
    # (upgraders only run after the snapshot renamed into place)
    tmp = os.path.join(directory, PREVIOUS_TMP)
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    flag = os.path.join(directory, UPGRADE_FLAG)
    if os.path.exists(flag):
        # crashed mid-UPGRADE.  After the snapshot renamed into place the
        # current tree may be half-migrated, but previous/ is the intact
        # pre-upgrade image — restore it (rollback also clears the flag,
        # which the snapshot excludes) and retry from scratch.  Before the
        # rename, the current tree is untouched: just clear the flag.
        if has_previous(directory):
            rollback(directory)
        if os.path.exists(flag):
            os.unlink(flag)
    v = read_version(directory)
    if v is None:
        entries = [e for e in os.listdir(directory)
                   if e not in (PREVIOUS, PREVIOUS_TMP)]
        if not entries:
            write_version(directory, kind, current)
            return current
        layout = 0          # pre-versioning store: implicit layout 0
    else:
        if v.get("storageType") not in (None, "", kind):
            raise LayoutError(
                f"{directory}: VERSION says storageType="
                f"{v.get('storageType')}, expected {kind}")
        layout = v["layoutVersion"]
    if layout > current:
        raise LayoutError(
            f"{directory}: on-disk layout {layout} is NEWER than this "
            f"binary's {kind} layout {current}; refusing to load "
            "(upgrade the software or roll the store back)")
    if layout == current:
        return current
    if has_previous(directory):
        # a COMPLETED earlier upgrade still awaits finalization; starting
        # another would overwrite the operator's rollback image with a
        # partially-newer tree (Storage.java's "previous upgrade in
        # progress" refusal)
        raise LayoutError(
            f"{directory}: layout {layout} needs an upgrade to {current} "
            "but an unfinalized previous/ snapshot exists — finalize (or "
            "roll back) the earlier upgrade first")
    with open(flag, "w", encoding="utf-8") as f:
        f.write(f"{layout}->{current}\n")
    _snapshot(directory)
    while layout < current:
        fn = (upgraders or {}).get(layout)
        if fn is None:
            raise LayoutError(
                f"{directory}: no upgrader registered for {kind} layout "
                f"{layout} -> {layout + 1}")
        fn(directory)
        layout += 1
        write_version(directory, kind, layout)
    os.unlink(flag)
    return layout


def has_previous(directory: str) -> bool:
    return os.path.isdir(os.path.join(directory, PREVIOUS))


def rollback(directory: str) -> None:
    """Restore the pre-upgrade tree byte-exactly from previous/ (the
    -rollback startup option).  The store must not be open."""
    prev = os.path.join(directory, PREVIOUS)
    if not os.path.isdir(prev):
        raise LayoutError(f"{directory}: no previous/ snapshot to roll "
                          "back to")
    for e in os.listdir(directory):
        if e in (PREVIOUS, PREVIOUS_TMP):
            continue
        p = os.path.join(directory, e)
        shutil.rmtree(p) if os.path.isdir(p) else os.unlink(p)
    for e in os.listdir(prev):
        os.replace(os.path.join(prev, e), os.path.join(directory, e))
    os.rmdir(prev)


def finalize_upgrade(directory: str) -> bool:
    """Drop the previous/ snapshot (dfsadmin -finalizeUpgrade): the
    upgrade becomes permanent, space is reclaimed.  Returns whether a
    snapshot existed."""
    prev = os.path.join(directory, PREVIOUS)
    if os.path.isdir(prev):
        shutil.rmtree(prev)
        return True
    return False


# ------------------------------------------------------------- upgraders

def dn_upgrade_0_to_1(directory: str) -> None:
    """Implicit pre-versioning store -> layout 1: just the VERSION file
    (contents unchanged)."""


def dn_upgrade_1_to_2(directory: str) -> None:
    """Flat replicas/containers -> per-volume layout: everything moves
    under volumes/vol-0/ (the first volume); the chunk index stays DN-wide
    at index/ (chunks are shared across volumes by design)."""
    vol0 = os.path.join(directory, "volumes", "vol-0")
    os.makedirs(vol0, exist_ok=True)
    for sub in ("replicas", "containers"):
        src = os.path.join(directory, sub)
        if os.path.isdir(src):
            os.replace(src, os.path.join(vol0, sub))


DN_UPGRADERS = {0: dn_upgrade_0_to_1, 1: dn_upgrade_1_to_2}

# NN/JN layout 1 is the VERSION file itself over the existing contents.
NN_UPGRADERS = {0: lambda d: None}
JN_UPGRADERS = {0: lambda d: None}
