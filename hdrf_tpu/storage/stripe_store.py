"""Erasure-coded stripe store: the EC(6,3) cold tier's storage half.

Re-expresses the reference's striped-block layout and reconstruction
plumbing (DFSStripedOutputStream.java:81 client striping;
StripedBlockUtil.java:77 logical<->stripe index math;
StripedBlockReconstructor.java:41 decode-and-writeback;
ErasureCodingWorker.java:55 DN-side reconstruction executor) TPU-first:
instead of striping the *raw* byte stream cell-by-cell at write time, we
RS-encode whole **sealed container files** — the already-reduced
(dedup'd + compressed) representation — so the EC savings multiply with
the reduction ratio (the compressed-coded-computing frame, arXiv
1805.01993).  Parity comes from ops/rs.py's Cauchy bit-matmul on the MXU
(rs.py:156), bit-identical to the GF log/antilog host oracle
(rs.py:134).

Layout: a sealed file of ``length`` bytes is zero-padded to
``k * stripe_len`` and split row-major into k data stripes; m parity
stripes are appended (indices k..k+m-1).  Each stripe carries a CRC32C
(native oracle, native/__init__.py:307) and the manifest records
``(k, m, length, stripe_len, crcs, holders)`` — enough to reassemble the
exact sealed bytes from ANY k surviving stripes.  Local stripe files are
keyed ``(owner_dn_id, cid, idx)`` because container ids are only unique
per owning DN.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Iterator

import numpy as np

from hdrf_tpu import native
from hdrf_tpu.ops import rs
from hdrf_tpu.utils import metrics

_M = metrics.registry("ec")


class StripeCorrupt(IOError):
    """A stripe's bytes fail its manifest CRC (treated as an erasure)."""


def encode_container(sealed: bytes, k: int, m: int) -> tuple[list[bytes], dict]:
    """RS-encode sealed container file bytes into k+m stripes.

    Returns ``(stripes, manifest)`` where ``stripes[i]`` is stripe index i
    (0..k-1 data, k..k+m-1 parity) and the manifest holds the geometry +
    per-stripe CRCs needed to reconstruct the exact input from any k
    survivors.  The input is zero-padded to a multiple of k (rs_encode
    reshapes to (k, -1)); ``length`` in the manifest is the TRUE sealed
    size, so reassembly truncates the pad away.
    """
    if k < 1 or m < 1:
        raise ValueError(f"bad EC geometry k={k} m={m}")
    length = len(sealed)
    stripe_len = max(1, -(-length // k))  # ceil; >=1 so empty still stripes
    padded = sealed + b"\x00" * (k * stripe_len - length)
    with _M.time("encode_us"):
        data = np.frombuffer(padded, dtype=np.uint8).reshape(k, stripe_len)
        parity = rs.rs_encode(data, k, m)
    stripes = [data[i].tobytes() for i in range(k)]
    stripes += [parity[i].tobytes() for i in range(m)]
    crcs = [native.crc32c(s) for s in stripes]
    _M.incr("stripes_encoded", k + m)
    _M.incr("containers_encoded")
    _M.incr("encode_logical_bytes", length)
    _M.incr("encode_physical_bytes", (k + m) * stripe_len)
    manifest = {"k": k, "m": m, "length": length,
                "stripe_len": stripe_len, "crcs": crcs}
    return stripes, manifest


def reconstruct_container(stripes: dict[int, bytes], manifest: dict,
                          want: list[int] | None = None) -> bytes | dict[int, bytes]:
    """Reassemble the sealed container bytes from >= k surviving stripes.

    CRC-verifies every offered stripe against the manifest (a corrupt
    stripe is an erasure, not an input — StripedBlockReconstructor
    treats checksum failures the same way), decodes any missing data
    indices through ops/rs.py's inverse bit-matmul, and truncates the
    zero pad back to ``length``.  With ``want`` set, returns the decoded
    stripes ``{idx: bytes}`` instead (the repair path: re-encode exactly
    the lost indices).
    """
    k, m = int(manifest["k"]), int(manifest["m"])
    length = int(manifest["length"])
    stripe_len = int(manifest["stripe_len"])
    crcs = list(manifest["crcs"])
    good: dict[int, np.ndarray] = {}
    for idx, raw in stripes.items():
        idx = int(idx)
        if len(raw) != stripe_len or native.crc32c(raw) != crcs[idx]:
            _M.incr("stripe_crc_errors")
            continue
        good[idx] = np.frombuffer(raw, dtype=np.uint8)
    if len(good) < k:
        raise StripeCorrupt(
            f"need {k} intact stripes, have {len(good)} of {len(stripes)}")
    if want is not None:
        with _M.time("decode_us"):
            out = rs.rs_decode(good, k, m, want=want)
        _M.incr("stripes_decoded", len(want))
        return {i: out[i].tobytes() for i in want}
    missing = [i for i in range(k) if i not in good]
    if missing:
        # a data stripe was lost: this read decodes through parity — the
        # cold tier's degraded-read counter lives HERE so every caller
        # (DN fallback, bench, tests) stamps the same registry
        _M.incr("degraded_reads")
        with _M.time("decode_us"):
            good.update(rs.rs_decode(good, k, m, want=missing))
        _M.incr("stripes_decoded", len(missing))
    blob = b"".join(good[i].tobytes() for i in range(k))
    return blob[:length]


class StripeStore:
    """Flat-file stripe storage for one DataNode (all volumes share it).

    Mirrors ContainerStore's on-disk discipline (container_store.py raw/
    sealed files): tmp-write + ``os.replace`` so a crash never leaves a
    half stripe, and ``physical_bytes()`` feeds the DN's capacity report.
    Stripes for containers owned by OTHER DNs land here too — that is the
    point of striping — hence the (owner, cid, idx) key.
    """

    def __init__(self, root: str) -> None:
        self._dir = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, owner: str, cid: int, idx: int) -> str:
        # owner ids are socket-safe tokens (host_port); keep them verbatim
        return os.path.join(self._dir, f"{owner}.{cid}.{idx}.stripe")

    def put_stripe(self, owner: str, cid: int, idx: int, payload: bytes,
                   crc: int | None = None) -> None:
        if crc is not None and native.crc32c(payload) != crc:
            _M.incr("stripe_crc_errors")
            raise StripeCorrupt(f"stripe {owner}/{cid}/{idx}: bad CRC on write")
        path = self._path(owner, cid, idx)
        tmp = path + ".tmp"
        with self._lock:
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        _M.incr("stripe_writes")
        _M.incr("stripe_bytes_written", len(payload))

    def read_stripe(self, owner: str, cid: int, idx: int) -> bytes:
        with open(self._path(owner, cid, idx), "rb") as f:
            data = f.read()
        _M.incr("stripe_reads")
        return data

    def has_stripe(self, owner: str, cid: int, idx: int) -> bool:
        return os.path.exists(self._path(owner, cid, idx))

    def local_indices(self, owner: str, cid: int) -> list[int]:
        """Stripe indices of (owner, cid) present on this DN's disk."""
        pfx = f"{owner}.{cid}."
        out = []
        for name in os.listdir(self._dir):
            if name.startswith(pfx) and name.endswith(".stripe"):
                out.append(int(name[len(pfx):-len(".stripe")]))
        return sorted(out)

    def delete_stripes(self, owner: str, cid: int) -> int:
        """Drop every local stripe of (owner, cid); returns bytes freed."""
        freed = 0
        with self._lock:
            for idx in self.local_indices(owner, cid):
                p = self._path(owner, cid, idx)
                try:
                    freed += os.path.getsize(p)
                    os.unlink(p)
                except FileNotFoundError:
                    pass
        _M.incr("stripe_bytes_deleted", freed)
        return freed

    def quarantine(self, owner: str, cid: int, idx: int) -> int:
        """Rename one scrub-confirmed corrupt stripe aside (``.quar``) so
        no gather/decode can pick it up again — reconstruct_container
        already CRC-filters corrupt stripes as erasures, but a renamed
        file also survives restarts and stops counting as a holder.
        Returns bytes quarantined."""
        p = self._path(owner, cid, idx)
        with self._lock:
            try:
                size = os.path.getsize(p)
                os.rename(p, p + ".quar")
            except OSError:
                return 0
        _M.incr("stripe_quarantined")
        return size

    def iter_stripes(self) -> Iterator[tuple[str, int, int, int]]:
        """Yield (owner, cid, idx, nbytes) for every local stripe file."""
        for name in sorted(os.listdir(self._dir)):
            if not name.endswith(".stripe"):
                continue
            stem = name[:-len(".stripe")]
            owner, cid_s, idx_s = stem.rsplit(".", 2)
            try:
                size = os.path.getsize(os.path.join(self._dir, name))
            except FileNotFoundError:
                continue
            yield owner, int(cid_s), int(idx_s), size

    def physical_bytes(self) -> int:
        return sum(size for *_ignored, size in self.iter_stripes())

    def stats(self) -> dict[str, Any]:
        n, total = 0, 0
        for *_ignored, size in self.iter_stripes():
            n += 1
            total += size
        return {"stripe_files": n, "stripe_physical_bytes": total}
