"""hdrf_tpu — a TPU-native distributed file system with transparent data reduction.

Built from scratch with the capability surface of NSWRyan/HDRF (an Apache Hadoop
HDFS 3.1.0 fork that performs content-defined-chunking deduplication and block
compression inside the DataNode write/read path). See ARCHITECTURE.md for the
component map and SURVEY.md for the reference analysis.

Subpackages:
    config     -- real configuration system (replaces DataNode.java:412-458 statics)
    native     -- ctypes bindings to libhdrf_native.so (C++ SHA-256/LZ4/CDC/CRC32C)
    ops        -- JAX/Pallas TPU kernels: CDC candidate scan, SHA-256 fingerprints
    parallel   -- multi-chip sharded reduction over jax.sharding.Mesh
    reduction  -- ReductionScheme plugin registry + schemes
    index      -- durable chunk/fingerprint index (replaces Redis)
    storage    -- replica dataset + chunk container store
    proto      -- wire protocol framing (control RPC + data transfer)
    server     -- namenode (metadata plane) + datanode (data plane)
    client     -- DFS client (put/get, write pipeline, read failover)
    tools      -- CLI
    utils      -- metrics, tracing, fault injection
    testing    -- MiniCluster in-process fixture, simulated dataset
"""

__version__ = "0.1.0"
