"""Configuration system.

Replaces the reference's compile-time statics (DataNode.java:412-458: ``modrun``,
``compressor``, ``hasher``, ``maxSize``, ``nRead``/``nWrite``, ``chunkDir``) and its
untouched Hadoop ``Configuration``/``hdfs-default.xml`` machinery with one typed,
layered config: defaults -> TOML file -> environment -> explicit overrides.

Key registry mirrors DFSConfigKeys.java / HdfsClientConfigKeys.java in spirit:
every tunable has a dotted key, a type, and a default, and is discoverable via
:func:`default_config`.
"""

from __future__ import annotations

import dataclasses
import os
try:
    import tomllib
except ModuleNotFoundError:      # Python < 3.11: the tomli backport is the
    import tomli as tomllib      # same parser under its pre-stdlib name
from dataclasses import dataclass, field
from typing import Any

ENV_PREFIX = "HDRF_"


@dataclass
class CdcConfig:
    """Content-defined chunking parameters.

    Reference fixed these at DataDeduplicator.java:264-307 (local-max window 700 B,
    max chunk 1 MB); BASELINE config 3 also exercises a window=48 / avg-8KB variant.
    """

    # Gear-hash boundary mask: boundary candidate when (hash & mask) == 0.
    # mask_bits=13 -> average chunk ~8 KiB.
    mask_bits: int = 13
    min_chunk: int = 2048
    max_chunk: int = 65536
    # Normalization: FastCDC-style two-mask scheme (stricter mask before the
    # average point, looser after) reduces chunk-size variance.
    normalized: bool = True

    @property
    def avg_chunk(self) -> int:
        return 1 << self.mask_bits


@dataclass
class ReductionConfig:
    """Reduction pipeline selection + resources.

    Replaces DataNode.java:438 ``compressor`` hardcoded switch and the per-scheme
    concurrency table at DataNode.java:499-510.
    """

    # Default scheme name for new files; overridable per-create by client policy.
    default_scheme: str = "dedup_lz4"
    # Max concurrent reduction jobs per datanode (admission control; replaces the
    # ticket queues at DataXceiver.java:313-380).
    max_concurrent_writes: int = 4
    max_concurrent_reads: int = 8
    # Streaming (direct-scheme) writes: wide like the reference's direct mode
    # (999 at DataNode.java:499-510) but still bounded.
    max_concurrent_direct: int = 64
    # Chunk container rollover size (reference: 2**25 at DataNode.java:434).
    container_size: int = 1 << 25
    # Compress containers on rollover (reference: LZ4 at DataDeduplicator.java:770-781).
    container_codec: str = "lz4"
    # Execution backend for the per-byte scans: "native" (C++), "tpu" (JAX/Pallas),
    # or "auto" (tpu when an accelerator is present).
    backend: str = "auto"
    # fsync container data files on append.  Default OFF — HDFS parity:
    # DataNodes do not fsync block data on finalize (durability comes from
    # replication; hsync is opt-in per client), and the scanner +
    # re-replication path covers post-crash chunk loss.  The index WAL is
    # always fsync'd (metadata integrity is not replication-recoverable).
    # CAUTION: because chunks are SHARED, an OS crash that loses one
    # container corrupts every dedup'd block referencing it on this DN; the
    # DN cross-checks index-vs-containers at startup and drops affected
    # blocks so peers re-replicate them — but at replication=1 there IS no
    # peer: set fsync_containers=True for replication=1 deployments.
    fsync_containers: bool = False
    # Co-located reduction worker (host, port): when set, the DN streams
    # block bytes to this separate worker PROCESS for CDC+SHA (and LZ4
    # container seals) instead of computing in-process — the north-star
    # deployment shape (BASELINE.json; bytes land in the worker's HBM as
    # they stream).  None = in-process compute via ``backend``.
    worker_addr: list | None = None
    # Per-op worker deadline budget: base seconds + a per-MiB term scaled by
    # payload size (replaces the reference's fixed 600 s socket timeout —
    # DataNode.java:436 ``socketTimeout`` has no payload awareness).  A hung
    # worker costs at most this budget before the DN falls back to the
    # in-process codec.  Generous defaults: the dev VM's write-burst
    # throttling stalls transports ~35 s (PERF_NOTES.md round 4).
    worker_deadline_s: float = 120.0
    worker_deadline_s_per_mb: float = 2.0
    # DN->worker circuit breaker: open after N consecutive WORKER failures
    # (caller-side iterator errors never count), half-open probe after
    # reset_s, re-close on probe success.  While open, writes skip the
    # connect entirely and reduce in-process (degraded passthrough).
    worker_breaker_failures: int = 3
    worker_breaker_reset_s: float = 10.0
    # DN-side worker supervision: when True the DN spawns its own
    # co-located reduction worker (spawn_local_worker) and respawns it
    # with capped backoff if it dies; worker_addr then names the LIVE
    # address and is updated on each respawn.
    worker_spawn: bool = False
    worker_respawn_base_s: float = 0.5
    worker_respawn_cap_s: float = 15.0
    # Device read path: reconstruction-heavy reads gather chunks from
    # HBM-resident container images (ops/reconstruct.py).  Default OFF:
    # it wins on PCIe/DMA-attached chips where repeat reads amortize the
    # image staging; through a slow D2H transport the host path is faster
    # (measured — PERF_NOTES.md).
    device_recon: bool = False
    # Async multi-block write pipeline (server/write_pipeline.py).
    # pipeline_depth: how many in-flight blocks one shared device batch may
    # coalesce (the ResidentReducer submit_many group bound); 1 = today's
    # serial one-block-at-a-time path, every pipeline stage bypassed.
    pipeline_depth: int = 4
    # Bounded WAL group-commit window (ms): concurrent commit_block calls
    # arriving within the window share ONE fsync (index/chunk_index.py).
    # Only armed when pipeline_depth > 1; 0 disables grouping outright.
    group_commit_window_ms: float = 2.0
    # Admission bound on blocks simultaneously inside the pipeline
    # (admitted-but-uncommitted); backpressures client streams beyond it.
    pipeline_max_inflight: int = 8
    # Mesh-sharded reduction plane (parallel/sharded.MeshReducer): when
    # True and >1 device is attached, coalesced groups run CDC+SHA+dedup
    # probe as ONE dispatch per mesh step, blocks data-parallel over the
    # whole mesh, with the device-resident sharded fingerprint bucket
    # table answering the dedup probe on-mesh.  The single-device serial
    # path stays verbatim as the bit-identity oracle.
    mesh_plane: bool = False
    # Per-device lane capacity: a mesh step coalesces up to
    # n_devices * mesh_lanes_per_device blocks.
    mesh_lanes_per_device: int = 2
    # Bucket slots PER DEVICE in the sharded fingerprint table (u32 pairs;
    # 2^15 slots = 256 KiB/device).  Collisions only cost a host re-check
    # or a duplicate append — never correctness.
    mesh_bucket_slots: int = 1 << 15
    # Coded mirror plane (server/mirror_plane.py): number of RS parity
    # segments cut over the reduced mirror payload.  0 = today's serial
    # relay through targets[0] (byte-identical path); m > 0 splits the
    # payload into k = n_targets - m data segments + m parity segments,
    # fans the legs out concurrently, and acks once ANY k land — a dead
    # or straggling mirror costs m/k extra bytes instead of a stall.
    mirror_parity: int = 0
    # Hedge trigger: parity legs launch when fewer than k data legs have
    # landed after (rolling-window p95 per-peer leg latency) * this
    # multiplier (the PR 3 peer windows feed the p95; no window data
    # falls back to mirror_hedge_floor_s).
    mirror_hedge_p95_mult: float = 3.0
    # Hedge-delay floor/fallback in seconds: used when the peer latency
    # windows have no samples yet, and as a lower bound so a cold window
    # never hedges at ~0 s.
    mirror_hedge_floor_s: float = 0.25
    # Read plane (server/read_plane.py): byte budget of the DN-wide
    # decoded-chunk cache, keyed by fingerprint so hits serve cross-file
    # as far as dedup reached.  0 disables the cache (plans still resolve
    # chunk-granular).
    chunk_cache_mb: float = 8.0
    # Read coalescer window (ms): concurrent readers' container-decode
    # misses arriving within the window decode through one batched
    # dispatch.  Only armed on the TPU backend with read_max_inflight > 1;
    # 0 decodes inline on the reader's thread (today's serial behavior).
    read_batch_window_ms: float = 2.0
    # Admission bound on plans simultaneously inside the read plane's
    # fetch stage (the read-side sibling of pipeline_max_inflight; the
    # DN-level max_concurrent_reads gate still applies outside it).
    read_max_inflight: int = 16
    # Per-tenant QoS admission (utils/qos.py): token-bucket refill rate in
    # MB/s and burst depth in MB, per tenant, shared across the DN's write
    # and read planes.  0 rate disables bucket-based admission (the
    # deadline shed below still applies); the bucket is a DEFICIT bucket —
    # admission charges nothing, actual bytes are debited after the op.
    qos_tenant_rate_mb_s: float = 0.0
    qos_tenant_burst_mb: float = 8.0
    # Deadline-aware load shedding: an op whose ambient ``_deadline``
    # budget cannot cover (rolling-p95 service time) * this multiplier is
    # refused AT ADMISSION with a retryable ShedError + retry-after hint,
    # instead of burning a slot to time out mid-pipeline.  Only fires when
    # the client sent a deadline AND the estimator has warmed up (≥5
    # samples in the 5-minute window).  0 disables.
    shed_p95_mult: float = 3.0
    # k+δ hedged stripe reads (server/ec_tier.py _gather): number of extra
    # stripe legs launched alongside the k primaries once the rolling
    # per-holder p95 leg latency (* mirror_hedge_p95_mult, floored at
    # mirror_hedge_floor_s) elapses — decode proceeds from the first k legs
    # to land, so one straggling holder never sets read latency.
    # 0 restores the serial holder-by-holder gather.
    ec_read_hedge_delta: int = 1
    # Coded-exchange shuffle plane (server/coded_exchange.py).
    # ec_coded_repair: stripe repair gathers partial SUMS instead of full
    # stripes — each surviving holder bit-matmuls its local stripes into a
    # GF-combined contribution and the chain XOR-folds them on the way back,
    # so the repairing owner ingests ~|missing| stripes of bytes instead of
    # k (ops/rs.py repair_rows/partial_sums).  False pins the classic full
    # gather (byte-identical output either way — the partial-sum fold IS
    # the decode, redistributed).
    ec_coded_repair: bool = True
    # LZ4-compress coded-exchange intermediates (repair contributions,
    # stripe pushes on demote/repair) via the batched compress path
    # (ops/dispatch.py block_compress_batch; on-TPU compress_many when the
    # backend resolves to tpu).  Negotiated per op: smaller-of ships, raw
    # wins ties, old peers that never asked get raw — False pins raw.
    coded_exchange_compress: bool = True
    # Mirror-plane segment legs (server/mirror_plane.py) ship
    # LZ4-compressed segments under the same smaller-of negotiation
    # (seg_crc always covers the RAW bytes).  False pins the old raw
    # path for A/B.
    mirror_compress_segments: bool = True
    # Content-adaptive chunk sizing (reduction/accounting.py
    # AdaptiveChunkController): the DN heartbeat observes the dedup
    # hit/miss counters and retunes cdc_mask_bits/min/max through the
    # live-reconfig path when a window of commits shows the corpus is
    # dedup-poor (coarsen) or dedup-rich (walk back toward the target).
    # Off by default: geometry then stays exactly the static CdcConfig.
    cdc_adaptive: bool = False
    # Floor under the controller's emitted min_chunk (the smallest cut
    # spacing any retune may select; the overflow-cap regression test pins
    # the fused kernel's fallback at this floor's smallest geometry).
    cdc_min_size: int = 512
    # The mask_bits the controller steps back toward when dedup yield is
    # healthy; 13 reproduces the shipped 2048/65536 geometry exactly.
    cdc_target_mask_bits: int = 13
    cdc: CdcConfig = field(default_factory=CdcConfig)


@dataclass
class NameNodeConfig:
    host: str = "127.0.0.1"
    port: int = 0  # 0 = ephemeral
    # Namespace persistence (FSImage.java:85 + FSEditLog.java:124 equivalents).
    meta_dir: str = "/tmp/hdrf/name"
    # Default replication factor & block size (hdfs-default.xml equivalents).
    replication: int = 3
    block_size: int = 128 * 1024 * 1024
    # Heartbeat bookkeeping (HeartbeatManager.java:44).
    heartbeat_interval_s: float = 1.0
    dead_node_interval_s: float = 6.0
    # How long a scheduled re-replication may stay in flight before the
    # monitor re-queues it (PendingReconstructionBlocks timeout analog).
    pending_replication_timeout_s: float = 30.0
    editlog_checkpoint_every: int = 1000  # ops between auto-checkpoints
    # Federation (multiple nameservices over one DN set,
    # BPOfferService.java:57): this NN's nameservice id and block-pool
    # index.  The block pool is an ID RANGE — block ids are allocated as
    # (pool_index << 48) | seq — so pools never collide and a DataNode
    # partitions its reports per nameservice with a shift (the role
    # BPOfferService's per-pool bookkeeping plays in the reference; chunk
    # containers stay DN-wide, so dedup even spans namespaces).
    nameservice_id: str = "ns0"
    block_pool_index: int = 0
    # HA: "active" serves + writes the journal; "standby" tails it read-only
    # and answers (possibly slightly stale) reads until failover; "observer"
    # tails like a standby but serves the read-only RPC set to clients with
    # a staleness bound (ObserverReadProxyProvider analog) and is never a
    # failover candidate.
    role: str = "active"
    # Standby journal catch-up cadence (EditLogTailer interval analog).
    tail_interval_s: float = 0.5
    # Observer read plane (design decision 19).  A read carrying a client
    # state-id the observer hasn't applied yet waits at most
    # observer_wait_s for the tailer to catch up, then bounces the call
    # back to the active (typed ObserverStaleError — never silently
    # stale).  Independently, reads are refused whenever the last
    # successful tail pass is older than observer_max_lag_s (the hard
    # staleness bound, dfs.ha.tail-edits.period + observer staleness
    # check analog).  observer_msync_wait_s bounds a parameterless
    # rpc_msync barrier.
    observer_wait_s: float = 0.25
    observer_max_lag_s: float = 5.0
    observer_msync_wait_s: float = 5.0
    # Block access tokens (dfs.block.access.token.enable analog): NN mints
    # HMAC tokens, DNs verify; keys ride heartbeat responses.
    block_tokens: bool = False
    # Enforce owner/group/mode + ACLs on namespace RPCs
    # (dfs.permissions.enabled analog).  The superuser (NN process owner)
    # and in-process callers always bypass.
    permissions_enabled: bool = True
    # Require a valid delegation token on client namespace RPCs
    # (hadoop.security.authentication=token analog; DN-protocol and
    # token-acquisition methods stay open — kerberos has no analog here).
    require_token_auth: bool = False
    # Startup safemode: hold mutations until this fraction of known blocks
    # has a reported replica (dfs.namenode.safemode.threshold-pct analog).
    safemode_threshold: float = 0.999
    # Quorum journal (dfs.namenode.shared.edits.dir=qjournal://... analog):
    # when set, edits live on this list of JournalNode (host, port) addrs
    # with majority-ack durability and only the fsimage stays in meta_dir;
    # when None, meta_dir is the (possibly NFS-shared) journal directory.
    journal_addrs: list | None = None
    # Peer NameNode control addrs — a quorum-mode standby that fell behind
    # the journal's purge horizon bootstraps its fsimage from a peer
    # (the standby-checkpointer image-transfer analog).
    peers: list | None = None
    # Observability status HTTP server (/prom, /traces, /stacks — the
    # HttpServer2 servlet-set analog); None = disabled.  0 = ephemeral port.
    status_port: int | None = None
    # Watchdog budget for in-flight RPCs (utils/watchdog.py).
    stall_budget_s: float = 30.0
    # Control-plane contention observatory (utils/lockprof.py): cap on
    # concurrent RPC handler connections — past it the accept loop parks
    # and a metadata storm backs up into the TCP listen queue instead of
    # spawning threads without bound (None = unbounded, the reference's
    # thread-per-connection default) — and the instrumented namesystem
    # lock's long-hold budget (stack captured + lockprof.long_hold fired
    # for any hold past it; the write-lock-reporting-threshold analog).
    rpc_max_handlers: int | None = None
    lock_long_hold_s: float = 0.5
    # EC cold tier (storage/stripe_store.py): sealed-container striping
    # geometry (ErasureCodingPolicy RS-k-m analog, default RS(6,3)) and
    # the demotion age: a complete, fully-replicated block whose file has
    # been idle this long is demoted from ``replication``x full copies to
    # (k+m)/k x stripes.  <= 0 disables demotion (default: the cold tier
    # is opt-in, like dfs.namenode.ec.system.default.policy being unset).
    ec_data_shards: int = 6
    ec_parity_shards: int = 3
    ec_demote_after_s: float = 0.0
    # Partial-replica reconciliation (coded mirror plane): how long a
    # scheduled upgrade re-push may stay in flight before the monitor
    # re-schedules it (the pending_replication_timeout_s analog for the
    # partial_replica -> full-replica lifecycle).
    partial_reconcile_timeout_s: float = 15.0
    # Flight recorder (utils/flight_recorder.py): fixed-cadence gauge
    # snapshots into a bounded ring, served as /timeseries.  interval <= 0
    # disables the sampler thread (the ring still answers, just empty
    # until sample_once is driven).
    flight_interval_s: float = 1.0
    flight_capacity: int = 512
    # Flight archive (utils/flight_archive.py): crash-safe JSONL
    # persistence of every flight sample, so daemon restarts keep the
    # long-horizon curve.  Empty dir disables; a relative dir resolves
    # under the metadata dir.  max_mb bounds the on-disk history (oldest
    # sealed segments GC'd first).
    flight_archive_dir: str = ""
    flight_archive_max_mb: int = 64


@dataclass
class DataNodeConfig:
    host: str = "127.0.0.1"
    port: int = 0
    data_dir: str = "/tmp/hdrf/data"
    # Topology label for rack-aware placement (net.topology mapping analog).
    rack: str = "/default-rack"
    # This DN's default storage type (StorageType enum analog: DISK/SSD/
    # ARCHIVE/RAM_DISK); storage POLICIES on paths select across nodes
    # and, with multiple volumes, across a node's volumes.
    storage_type: str = "DISK"
    # Per-volume storage types (dfs.datanode.data.dir's [SSD]/path list
    # analog): each entry creates volumes/vol-i of that type under
    # data_dir.  None = one volume of ``storage_type``.
    volume_types: list | None = None
    # Packet size on the data-transfer wire (reference default 64 KB).
    packet_size: int = 64 * 1024
    # Pinned replica cache budget (dfs.datanode.max.locked.memory analog).
    cache_capacity: int = 64 * 1024 * 1024
    heartbeat_interval_s: float = 1.0
    block_report_interval_s: float = 30.0
    # Rolling replica verification cadence (BlockScanner analog); one block
    # verified per tick, 0 disables.
    scan_interval_s: float = 30.0
    # Volume health probe cadence (DatasetVolumeChecker analog); 0 disables.
    volume_check_interval_s: float = 15.0
    # RAM-backed fake dataset for protocol tests at scale
    # (SimulatedFSDataset analog).
    simulated_dataset: bool = False
    # Require + speak the encrypted data-transfer handshake
    # (dfs.encrypt.data.transfer): plaintext ops are refused, and this DN's
    # own outgoing legs (mirroring, transfers, reconstruction) encrypt.
    encrypt_data_transfer: bool = False
    # Cap on BACKGROUND transfer legs — balancer moves, NN-commanded
    # re-replication, EC reconstruction fan-in — in bytes/s
    # (dfs.datanode.balance.bandwidthPerSec analog; the reference defaults
    # to 100 MB/s).  0 disables.  Live-reconfigurable, and settable
    # cluster-wide via ``dfsadmin -setBalancerBandwidth``.
    balancer_bandwidth: int = 100 * 1024 * 1024
    # Lazy-persist (RAM_DISK) machinery: the lazy writer copies RAM
    # replicas to DISK every this many seconds (0 disables; the loop only
    # starts when a RAM_DISK volume is configured), and evicts persisted
    # RAM copies once the RAM volume exceeds the capacity budget
    # (dfs.datanode.ram.disk.low.watermark analog, expressed as a cap).
    lazy_writer_interval_s: float = 3.0
    ram_disk_capacity: int = 64 * 1024 * 1024
    # Provided-storage mount root: ``alias_add`` file:// URIs must resolve
    # inside this directory or the region is rejected (without it, anyone
    # holding a write token could alias a block to an arbitrary DN-local
    # file — /etc/passwd disclosure through the ordinary read path).
    # Empty = provided storage disabled for file:// URIs; "/" opts out of
    # confinement explicitly.
    provided_mount_root: str = ""
    # Observability status HTTP server (/prom, /traces, /stacks — the
    # HttpServer2 servlet-set analog); None = disabled.  0 = ephemeral port.
    status_port: int | None = None
    # Watchdog budget for in-flight data-transfer ops (utils/watchdog.py):
    # flags ops outliving this many seconds (the ~35 s VM write-burst
    # stalls, PERF_NOTES.md).
    stall_budget_s: float = 30.0
    # Flight recorder (utils/flight_recorder.py): fixed-cadence gauge
    # snapshots into a bounded ring, served as /timeseries.  interval <= 0
    # disables the sampler thread.
    flight_interval_s: float = 1.0
    flight_capacity: int = 512
    # Flight archive (utils/flight_archive.py): crash-safe JSONL
    # persistence of flight samples (restart-surviving /timeseries).
    # Empty dir disables; a relative dir resolves under data_dir.
    flight_archive_dir: str = ""
    flight_archive_max_mb: int = 64
    # Continuous integrity scrub (server/scrubber.py): background cycle
    # re-verifying sealed containers / EC stripes / replica invariants and
    # taking the garbage census.  interval <= 0 disables the loop (the
    # default: tests and operators opt in); the rate cap bounds scrub disk
    # reads (VolumeScanner's dfs.block.scanner.volume.bytes.per.second
    # analog); sample_frac is the fraction of a container's live chunks
    # digest-verified per cycle (1.0 = every chunk).
    scrub_interval_s: float = 0.0
    scrub_rate_mb_s: float = 8.0
    scrub_sample_frac: float = 0.25
    # Crashed tmp+fsync+replace writes (container seal, stripe put,
    # mirror-segment put) leave *.tmp orphans; the scrubber reclaims ones
    # older than this (young tmps may still be mid-replace).
    scrub_tmp_age_s: float = 300.0
    reduction: ReductionConfig = field(default_factory=ReductionConfig)


@dataclass
class ClientConfig:
    packet_size: int = 64 * 1024
    # Outstanding un-acked packets in the write pipeline (DataStreamer window).
    max_inflight_packets: int = 16
    read_retries: int = 3
    # Short-circuit local reads: fd passing over the DN's unix socket
    # (dfs.client.read.shortcircuit analog).
    short_circuit: bool = True
    # Encrypt block data on the wire (dfs.encrypt.data.transfer analog);
    # needs block tokens enabled — the token signature keys the handshake.
    encrypt_data_transfer: bool = False
    # Fetch a delegation token at connect and attach it to every NameNode
    # RPC (the kerberos-bootstrapped token flow, minus kerberos).
    use_delegation_tokens: bool = False
    # End-to-end deadline budget (seconds) bound around each write/read op
    # and propagated hop-by-hop as the _deadline header (utils/retry.py).
    # None = no client-imposed budget (default: the dev VM's write-burst
    # throttling stalls ~35 s, so budgets are strictly opt-in).
    op_deadline_s: float | None = None
    # Hedged replica reads (utils/retry.hedged_quorum): when a block has
    # >1 location, the second location launches as a tied request once the
    # first exceeds (rolling-window p95 block-read latency) * mult, or
    # immediately on primary failure.  False restores the serial failover
    # loop verbatim.
    hedged_reads: bool = True
    read_hedge_p95_mult: float = 3.0
    # Hedge-delay floor/fallback (s): used before the latency window has
    # samples, and as a lower bound so a cold window never hedges at ~0 s.
    read_hedge_floor_s: float = 0.05
    # Observer reads (ObserverReadProxyProvider analog): route read-only
    # NameNode RPCs to observer endpoints first, carrying last_seen_txid
    # for read-your-writes.  No-op when the endpoint list has no observer.
    observer_reads: bool = True
    # Client-side metadata cache (block locations + stats, LRU with TTL)
    # invalidated by txid generation: an entry is served only while the
    # client has observed NO newer journal txid than at insert time, so
    # any mutation this client sees (its own writes included — replies
    # piggyback the txid) invalidates at once.  ttl <= 0 disables (the
    # default: block locations are soft state, so caching is opt-in for
    # read-hot workloads that tolerate bounded staleness).
    metadata_cache_ttl_s: float = 0.0
    metadata_cache_entries: int = 256


@dataclass
class HdrfConfig:
    namenode: NameNodeConfig = field(default_factory=NameNodeConfig)
    datanode: DataNodeConfig = field(default_factory=DataNodeConfig)
    client: ClientConfig = field(default_factory=ClientConfig)

    # ---- layered loading -------------------------------------------------

    @staticmethod
    def load(path: str | None = None, env: dict[str, str] | None = None,
             overrides: dict[str, Any] | None = None) -> "HdrfConfig":
        cfg = HdrfConfig()
        if path:
            with open(path, "rb") as f:  # explicit path must exist
                cfg._apply_mapping(tomllib.load(f))
        cfg._apply_env(os.environ if env is None else env)
        if overrides:
            for k, v in overrides.items():
                cfg.set(k, v)
        return cfg

    def _apply_mapping(self, m: dict[str, Any], prefix: str = "") -> None:
        for k, v in m.items():
            key = f"{prefix}{k}" if not prefix else f"{prefix}.{k}"
            if isinstance(v, dict):
                self._apply_mapping(v, key)
            else:
                self.set(key, v)

    def _apply_env(self, env: dict[str, str]) -> None:
        # HDRF_DATANODE_REDUCTION_DEFAULT_SCHEME=zstd -> datanode.reduction.default_scheme
        for name, raw in env.items():
            if not name.startswith(ENV_PREFIX):
                continue
            key = name[len(ENV_PREFIX):].lower().replace("_", ".")
            try:
                self.set(key, _parse_scalar(raw))
            except KeyError:
                continue  # unknown env keys are ignored, like Hadoop's

    def set(self, dotted_key: str, value: Any) -> None:
        """Set a value by dotted key.

        Env-style keys can't distinguish '.' from '_' (both arrive as '.'), so
        matching greedily joins leading segments against field names:
        ``datanode.reduction.default.scheme`` resolves to
        ``datanode.reduction.default_scheme``.
        """
        _dotted_set(self, dotted_key.split("."), dotted_key, value)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def _dotted_set(obj: Any, parts: list[str], full_key: str, value: Any) -> None:
    fields = {f.name for f in dataclasses.fields(obj)}
    for j in range(len(parts), 0, -1):
        cand = "_".join(parts[:j])
        if cand not in fields:
            continue
        cur = getattr(obj, cand)
        if j == len(parts):
            if dataclasses.is_dataclass(cur):
                raise KeyError(f"{full_key!r} names a section, not a value")
            setattr(obj, cand, _coerce(value, type(cur)))
            return
        if dataclasses.is_dataclass(cur):
            return _dotted_set(cur, parts[j:], full_key, value)
    raise KeyError(f"unknown config key: {full_key!r}")


def _coerce(value: Any, typ: type | None) -> Any:
    if typ is None or isinstance(value, typ):
        return value
    if typ is bool:
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if typ in (int, float, str):
        return typ(value)
    return value


def _parse_scalar(raw: str) -> Any:
    for conv in (int, float):
        try:
            return conv(raw)
        except ValueError:
            pass
    if raw.lower() in ("true", "false"):
        return raw.lower() == "true"
    return raw


def default_config() -> HdrfConfig:
    return HdrfConfig()
