"""OAuth2 access-token providers for the WebHDFS-over-HTTP surface.

Re-expression of the reference's ``web/oauth2`` package —
``AccessTokenProvider.java:36`` (the provider abstraction + cache),
``ConfCredentialBasedAccessTokenProvider.java`` (client-credentials grant)
and ``ConfRefreshTokenBasedAccessTokenProvider.java`` (refresh-token grant),
``AccessTokenTimer.java`` (expiry tracking with a refresh margin) — over
urllib instead of OkHttp.  The provider hands back a bearer token the HTTP
client attaches as ``Authorization: Bearer <token>``; the gateway side
validates bearers via RFC 7662 token introspection (see
server/http_gateway.py) so a stub IdP can drive the whole path in tests.
"""

from __future__ import annotations

import json
import time
import urllib.parse
import urllib.request

# refresh this many seconds BEFORE expiry (AccessTokenTimer.EXPIRE_BUFFER_MS)
EXPIRE_BUFFER_S = 30.0


class AccessTokenProvider:
    """Caches an access token until shortly before expiry; subclasses
    implement ``_fetch() -> (token, expires_in_s)``."""

    def __init__(self) -> None:
        self._token: str | None = None
        self._expiry = 0.0

    def access_token(self) -> str:
        if self._token is None or time.time() >= self._expiry:
            token, ttl = self._fetch()
            self._token = token
            self._expiry = time.time() + max(ttl - EXPIRE_BUFFER_S, 1.0)
        return self._token

    def _fetch(self) -> tuple[str, float]:  # pragma: no cover - abstract
        raise NotImplementedError


def _token_request(url: str, form: dict) -> tuple[str, float]:
    body = urllib.parse.urlencode(form).encode()
    req = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type": "application/x-www-form-urlencoded"})
    with urllib.request.urlopen(req, timeout=30) as r:
        out = json.loads(r.read())
    if "access_token" not in out:
        raise PermissionError(f"IdP returned no access_token: {out}")
    return out["access_token"], float(out.get("expires_in", 3600))


class ConfCredentialBasedAccessTokenProvider(AccessTokenProvider):
    """client_credentials grant from configured id+secret
    (ConfCredentialBasedAccessTokenProvider.java)."""

    def __init__(self, token_url: str, client_id: str, client_secret: str):
        super().__init__()
        self._url = token_url
        self._id = client_id
        self._secret = client_secret

    def _fetch(self) -> tuple[str, float]:
        return _token_request(self._url, {
            "grant_type": "client_credentials",
            "client_id": self._id, "client_secret": self._secret})


class ConfRefreshTokenBasedAccessTokenProvider(AccessTokenProvider):
    """refresh_token grant from a configured long-lived refresh token
    (ConfRefreshTokenBasedAccessTokenProvider.java)."""

    def __init__(self, token_url: str, client_id: str, refresh_token: str):
        super().__init__()
        self._url = token_url
        self._id = client_id
        self._refresh = refresh_token

    def _fetch(self) -> tuple[str, float]:
        return _token_request(self._url, {
            "grant_type": "refresh_token",
            "client_id": self._id, "refresh_token": self._refresh})
