"""Client: filesystem API over the control RPC + data transfer protocol.

Re-expression of the reference's client stack — DistributedFileSystem ->
DFSClient (DFSClient.java:204; open :967, create :1116), DFSOutputStream +
DataStreamer (block write pipeline, DataStreamer.java:655, pipeline setup
:1655/:1702), DFSInputStream (read with location failover,
DFSInputStream.java:817 -> blockSeekTo :539) — as a compact synchronous
client:

- ``write``: create -> per block: add_block -> stream packets to the first
  target (which mirrors downstream) -> final aggregated ack -> complete.
  Pipeline failure recovery is block-granular: abandon the block and
  re-request targets (the reference swaps the bad node mid-block,
  DataStreamer pipeline recovery; block-granular retry is the simpler
  equivalent with identical durability).
- ``read``: get_block_locations -> per block: try each replica location in
  order, failing over on connection/checksum errors (read failover,
  DFSInputStream.java:621+).  Range reads request only the overlapping
  blocks and byte ranges (reconstruction stays chunk-granular end-to-end).
- observer metadata plane (ISSUE 20): reads route to observer NNs through
  the HA proxy's state-id protocol (ObserverReadProxyProvider.java:60),
  ``msync`` exposes the consistency barrier, and an opt-in LRU+TTL
  metadata cache (block locations + stats) is invalidated by txid
  generation, so hot-path re-reads skip the NN fleet entirely.
"""

from __future__ import annotations

import collections
import socket
import threading
import time
import uuid

from hdrf_tpu import native
from hdrf_tpu.config import ClientConfig
from hdrf_tpu.proto import datatransfer as dt
from hdrf_tpu.proto.rpc import RpcClient, recv_frame
from hdrf_tpu.utils import metrics, qos, retry, rollwin, tracing

_M = metrics.registry("client")
_TR = tracing.tracer("client")


class HdrfClient:
    def __init__(self, namenode_addr,
                 config: ClientConfig | None = None, name: str | None = None,
                 user: str | None = None, groups: list[str] | None = None):
        """``namenode_addr``: one (host, port) or an ordered list of them —
        a list engages the HA failover proxy (retry across NNs on
        StandbyError / connection failure).  ``user``/``groups``: the
        caller identity presented to the NameNode's permission checker
        (UGI analog); defaults to the OS user."""
        import getpass

        self.config = config or ClientConfig()
        self.name = name or f"client-{uuid.uuid4().hex[:8]}"
        self.user = user or getpass.getuser()
        self.groups = list(groups or [])
        from hdrf_tpu.proto.rpc import HaRpcClient, normalize_addrs

        addrs = normalize_addrs(namenode_addr)
        self._nn = (HaRpcClient(addrs,
                                observer_reads=self.config.observer_reads)
                    if len(addrs) > 1 else RpcClient(addrs[0]))
        self._sc_cache = None  # lazy ShortCircuitCache (fd + shm slots)
        # Client-side metadata cache (block locations + stats; LRU with
        # TTL) invalidated by txid GENERATION: entries remember the
        # highest journal txid this client had observed at insert and are
        # served only while that hasn't moved — any mutation the client
        # sees (its own writes included, via the reply-envelope state
        # stamp) invalidates the whole generation at once.  Off unless
        # metadata_cache_ttl_s > 0.
        self._meta_cache: collections.OrderedDict = collections.OrderedDict()
        self._meta_lock = threading.Lock()
        # Rolling window of successful block-read latencies: its p95 sets
        # the hedged-read trigger (utils/rollwin.py, the same discipline
        # as the mirror plane's per-peer hedge windows).
        self._read_lat = rollwin.RollingWindow(window_s=300.0, maxlen=128)
        self._dtoken: dict | None = None
        if self.config.use_delegation_tokens:
            self._dtoken = self._nn.call("get_delegation_token",
                                         renewer=self.name, owner=self.name)

    def _op_deadline(self):
        """End-to-end budget for one public op: binds the ambient deadline
        (propagated hop-by-hop as the _deadline header by RpcClient and
        dt.send_op) when ``ClientConfig.op_deadline_s`` is set; otherwise a
        no-op that leaves any caller-bound deadline in place."""
        import contextlib as _ctx

        b = self.config.op_deadline_s
        if not b:
            return _ctx.nullcontext()
        return retry.bind(retry.Deadline(float(b)))

    def _call(self, method: str, **kw):
        """NameNode RPC with the client's delegation token and caller
        identity attached (the UGI-token-selector analog: every call
        authenticates — and is permission-checked — when the cluster
        requires it).  Paths through symlinks answer SymlinkRedirect with
        the resolved path; the client retries, bounded (the reference's
        UnresolvedPathException client-side resolution)."""
        from hdrf_tpu.proto.rpc import RpcError

        if self._dtoken is not None:
            kw["_dtoken"] = self._dtoken
        kw["_user"] = self.user
        kw["_client"] = self.name  # tenant attribution (utils/tenants.py)
        if self.groups:
            kw["_groups"] = self.groups
        for _ in range(16):
            try:
                return self._nn.call(method, **kw)
            except RpcError as e:
                if e.error != "SymlinkRedirect":
                    raise
                orig, _, resolved = e.message.partition("\n")

                def norm(p):
                    return "/" + "/".join(x for x in str(p).split("/") if x)

                hit = False
                for k, v in list(kw.items()):
                    if isinstance(v, str) and not k.startswith("_") \
                            and norm(v) == orig:
                        kw[k] = resolved
                        hit = True
                    elif isinstance(v, list) and v and \
                            all(isinstance(x, str) for x in v):
                        kw[k] = [resolved if norm(x) == orig else x
                                 for x in v]
                        hit = hit or kw[k] != v
                if not hit:
                    raise
        raise IOError("too many levels of symbolic links")

    def _cached_meta(self, method: str, path: str):
        """``stat``/``get_block_locations`` through the LRU+TTL metadata
        cache.  A hit requires the entry to be unexpired AND inserted at
        the client's CURRENT txid generation — ``last_seen_txid`` advances
        on every reply that observed a newer journal state, so a bumped
        generation invalidates everything older in one comparison."""
        ttl = self.config.metadata_cache_ttl_s
        if ttl <= 0:
            return self._call(method, path=path)
        gen = getattr(self._nn, "last_seen_txid", 0)
        key = (method, path)
        now = time.monotonic()
        with self._meta_lock:
            ent = self._meta_cache.get(key)
            if ent is not None and ent[0] > now and ent[1] == gen:
                self._meta_cache.move_to_end(key)
                _M.incr("meta_cache_hits")
                return ent[2]
        _M.incr("meta_cache_misses")
        out = self._call(method, path=path)
        gen = getattr(self._nn, "last_seen_txid", 0)  # post-reply generation
        with self._meta_lock:
            self._meta_cache[key] = (now + ttl, gen, out)
            self._meta_cache.move_to_end(key)
            while len(self._meta_cache) > self.config.metadata_cache_entries:
                self._meta_cache.popitem(last=False)
        return out

    def msync(self, wait_s: float | None = None) -> dict:
        """Consistency barrier (FileSystem.msync analog): wait until every
        reachable observer has applied this client's last-seen txid, so
        subsequent observer reads are read-your-writes.  A single-NN
        client talks straight to the active — already consistent — and
        returns {}."""
        ms = getattr(self._nn, "msync", None)
        return ms(wait_s=wait_s) if ms is not None else {}

    def renew_delegation_token(self) -> float:
        return self._call("renew_delegation_token", token=self._dtoken)

    def cancel_delegation_token(self) -> bool:
        out = self._call("cancel_delegation_token", token=self._dtoken)
        self._dtoken = None
        return out

    def close(self) -> None:
        if self._sc_cache is not None:
            self._sc_cache.close()
            self._sc_cache = None
        self._nn.close()

    def __enter__(self) -> "HdrfClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---------------------------------------------------------- namespace ops

    def mkdir(self, path: str) -> bool:
        return self._call("mkdir", path=path)

    @staticmethod
    def _trash_root() -> str:
        """Keyed to the OS user (fs.trash keys on the HDFS user the same
        way) — NOT the per-process client id, or every CLI invocation would
        orphan its own trash dir."""
        import getpass

        return f"/.Trash/{getpass.getuser()}"

    def delete(self, path: str, skip_trash: bool = True) -> bool:
        """``skip_trash=False`` moves into the user's trash instead of
        deleting (the fs.trash interval behavior; `expunge` empties).  Paths
        already inside the trash are always deleted permanently."""
        if skip_trash or path.startswith("/.Trash/"):
            return self._call("delete", path=path)
        import time as _t

        if not self.exists(path):
            return False  # same contract as the direct delete
        name = path.strip("/").replace("/", "%2F")
        base = f"{self._trash_root()}/{int(_t.time())}-{name}"
        for attempt in range(100):  # same-second re-delete of a recreated
            # path: disambiguate like HDFS's .1/.2 suffixes
            dst = base if attempt == 0 else f"{base}.{attempt}"
            try:
                return self._call("rename", src=path, dst=dst)
            except Exception as e:
                if getattr(e, "error", "") != "FileExistsError":
                    raise
        raise IOError(f"could not find a free trash slot for {path}")

    def expunge(self, older_than_s: float = 0.0) -> int:
        """Delete trash entries older than ``older_than_s`` (dfs -expunge)."""
        import time as _t

        removed = 0
        try:
            entries = self.ls(self._trash_root())
        except Exception as e:
            if getattr(e, "error", "") == "FileNotFoundError":
                return 0  # nothing ever trashed
            raise
        cutoff = _t.time() - older_than_s
        for e in entries:
            try:
                ts = int(e["name"].split("-", 1)[0].split(".", 1)[0])
            except ValueError:
                continue
            if ts <= cutoff:
                if self._call(
                        "delete", path=f"{self._trash_root()}/{e['name']}"):
                    removed += 1
        return removed

    def rename(self, src: str, dst: str) -> bool:
        return self._call("rename", src=src, dst=dst)

    def ls(self, path: str) -> list[dict]:
        return self._call("listing", path=path)

    def stat(self, path: str) -> dict:
        return self._cached_meta("stat", path)

    def exists(self, path: str) -> bool:
        try:
            self._call("stat", path=path)
            return True
        except Exception:
            return False

    def datanode_report(self) -> list[dict]:
        return self._call("datanode_report")

    # ------------------------------------------------------ cache directives

    def add_cache_pool(self, name: str, limit: int = -1) -> bool:
        return self._call("add_cache_pool", name=name, limit=limit)

    def remove_cache_pool(self, name: str) -> bool:
        return self._call("remove_cache_pool", name=name)

    def list_cache_pools(self) -> dict:
        return self._call("list_cache_pools")

    def add_cache_directive(self, path: str, pool: str) -> int:
        return self._call("add_cache_directive", path=path, pool=pool)

    def remove_cache_directive(self, directive_id: int) -> bool:
        return self._call("remove_cache_directive",
                          directive_id=directive_id)

    def list_cache_directives(self) -> list[dict]:
        return self._call("list_cache_directives")

    # ------------------------- storage policy / replication / times / links

    def set_storage_policy(self, path: str, policy: str) -> bool:
        return self._call("set_storage_policy", path=path, policy=policy)

    def get_storage_policy(self, path: str) -> dict:
        return self._call("get_storage_policy", path=path)

    def set_replication(self, path: str, replication: int) -> bool:
        return self._call("set_replication", path=path,
                          replication=replication)

    def set_times(self, path: str, mtime: float = -1.0) -> bool:
        return self._call("set_times", path=path, mtime=mtime)

    def concat(self, dst: str, srcs: list[str]) -> bool:
        return self._call("concat", dst=dst, srcs=srcs)

    def create_symlink(self, link: str, target: str) -> bool:
        return self._call("create_symlink", link=link, target=target)

    # -------------------------------------- permissions / ACLs / xattrs

    def chmod(self, path: str, mode: int) -> bool:
        return self._call("set_permission", path=path, mode=mode)

    def chown(self, path: str, owner: str = "", group: str = "") -> bool:
        return self._call("set_owner", path=path, owner=owner, group=group)

    def getfacl(self, path: str) -> dict:
        return self._call("get_acl", path=path)

    def setfacl(self, path: str, spec: str = "", default_spec: str = "",
                remove_all: bool = False,
                remove_default: bool = False) -> bool:
        return self._call("set_acl", path=path, spec=spec,
                          default_spec=default_spec, remove_all=remove_all,
                          remove_default=remove_default)

    def setfattr(self, path: str, name: str, value: bytes) -> bool:
        return self._call("set_xattr", path=path, name=name, value=value)

    def getfattr(self, path: str, names: list[str] | None = None) -> dict:
        return self._call("get_xattrs", path=path, names=names)

    def removefattr(self, path: str, name: str) -> bool:
        return self._call("remove_xattr", path=path, name=name)

    # ------------------------------------------------- snapshots and quotas

    def allow_snapshot(self, path: str) -> bool:
        return self._call("allow_snapshot", path=path)

    def create_snapshot(self, path: str, name: str) -> bool:
        return self._call("create_snapshot", path=path, name=name)

    def delete_snapshot(self, path: str, name: str) -> bool:
        return self._call("delete_snapshot", path=path, name=name)

    def list_snapshots(self, path: str) -> list[str]:
        return self._call("list_snapshots", path=path)

    def snapshot_diff(self, path: str, from_snap: str,
                      to_snap: str = "") -> dict:
        """Diff report between two snapshots (getSnapshotDiffReport,
        SnapshotDiffInfo.java:44); empty ``to_snap`` diffs against the
        current tree.  Entries: {type: CREATE|DELETE|MODIFY|RENAME, path,
        [target]} with paths relative to the snapshot root."""
        return self._call("snapshot_diff", path=path, from_snap=from_snap,
                          to_snap=to_snap)

    def set_quota(self, path: str, namespace_quota: int = -1,
                  space_quota: int = -1) -> bool:
        return self._call("set_quota", path=path,
                             namespace_quota=namespace_quota,
                             space_quota=space_quota)

    def content_summary(self, path: str) -> dict:
        return self._call("content_summary", path=path)

    def events(self, since_seq: int = 0, poll_s: float = 0.2):
        """Namespace event iterator (DFSInotifyEventInputStream analog):
        yields event dicts forever; break when done.  Raises IOError when the
        server's ring trimmed events past this consumer (the
        MissingEventsException analog) — resync via a listing and a fresh
        iterator."""
        import time as _t

        seq = since_seq
        while True:
            resp = self._call("get_events", since_seq=seq)
            if seq and resp["trimmed_through"] > seq:
                raise IOError(
                    f"event stream gap: events through "
                    f"{resp['trimmed_through']} were trimmed, consumer at "
                    f"{seq}")
            for ev in resp["events"]:
                yield ev
                seq = ev["seq"]
            if not resp["events"]:
                # no events in (seq, last_seq]: those edits emit no events,
                # so skipping ahead is safe and keeps the next poll cheap
                seq = max(seq, resp["last_seq"])
                _t.sleep(poll_s)

    # ----------------------------------------------------------------- write

    def open_for_write(self, path: str,
                       replication: int | None = None) -> "HdrfOutputStream":
        """Open a streaming writer with hflush/hsync support
        (DFSOutputStream.java:573 hflush / :580 hsync — the mid-write
        durability API WAL-shaped workloads depend on).  Blocks written
        through the stream are stored under the ``direct`` scheme: bytes
        must reach replicas incrementally, which is incompatible with
        whole-block reduction (the reference likewise reduces only blocks
        that arrive whole)."""
        info = self._call("create", path=path, client=self.name,
                          replication=replication, scheme="direct")
        if info.get("encryption"):
            raise IOError("streaming writes inside encryption zones are "
                          "not supported (use write())")
        return HdrfOutputStream(self, path, info["block_size"])

    def write(self, path: str, data: bytes, scheme: str | None = None,
              replication: int | None = None, ec: str | None = None) -> None:
        """Write a whole file (the put path, §3.1 of SURVEY.md).  ``ec`` is an
        erasure-coding policy name ('rs-6-3-64k'): the file is cell-striped
        over k+m DataNodes instead of replicated (client/striped.py)."""
        with self._op_deadline(), _TR.span("write") as sp:
            sp.annotate("path", path)
            sp.annotate("bytes", len(data))
            if ec is not None:
                from hdrf_tpu.client.striped import StripedWriter

                StripedWriter(self).write(path, data, ec)
                _M.incr("files_written")
                return
            info = self._call("create", path=path, client=self.name,
                                 replication=replication, scheme=scheme)
            if info.get("encryption"):
                # transparent client-side encryption (the DFSClient
                # CryptoOutputStream role): ChaCha20 stream over the file
                # bytes under the per-file DEK; the DN stores ciphertext
                enc = info["encryption"]
                data = native.chacha20_xor(bytes(enc["dek"]),
                                           bytes(enc["iv"]), data)
                _M.incr("encrypted_writes")
            block_size = info["block_size"]
            lengths: dict[int, int] = {}
            off = 0
            import time as _t

            last_renew = _t.monotonic()
            while True:
                block = data[off:off + block_size]
                bid = self._write_block(path, block)
                lengths[bid] = len(block)
                off += block_size
                # LeaseRenewer analog: time-based, at 1/3 of the 60 s lease
                # expiry — a slow write must not outlive its lease
                if _t.monotonic() - last_renew > 20.0:
                    self._call("renew_lease", client=self.name)
                    last_renew = _t.monotonic()
                if off >= len(data):
                    break
            self._complete(path, lengths)
            _M.incr("files_written")
            _M.incr("bytes_written", len(data))

    def append(self, path: str, data: bytes) -> None:
        """Append to a complete file (DFSClient.append analog).  The last
        partial block is REWRITTEN under a bumped generation stamp
        (block-granular copy-on-append — the design that stays coherent
        with reduced storage; the re-reduction dedups against the block's
        own old chunks), full blocks are appended as usual."""
        if not data:
            return
        with _TR.span("append") as sp:
            sp.annotate("path", path)
            info = self._call("append", path=path, client=self.name)
            block_size = info["block_size"]
            lengths: dict[int, int] = {}
            last = info.get("last_block")
            if last is not None:
                # prefix = the partial last block's current bytes
                prefix = self.read(path, offset=info["file_length"]
                                   - last["length"], length=last["length"])
                merged = prefix + data[:block_size - last["length"]]
                alloc = self._call("append_block", path=path,
                                   client=self.name)
                self._stream_block(alloc, merged)
                lengths[alloc["block_id"]] = len(merged)
                data = data[block_size - last["length"]:]
            off = 0
            while off < len(data):
                block = data[off:off + block_size]
                lengths[self._write_block(path, block)] = len(block)
                off += block_size
            self._complete(path, lengths)
            _M.incr("appends")

    def truncate(self, path: str, new_length: int) -> bool:
        return self._call("truncate", path=path, new_length=new_length)

    def _complete(self, path: str, lengths: dict[int, int],
                  timeout: float = 30.0) -> None:
        """completeFile retry loop: the NN answers False until every block
        has a reported location (IBRs are asynchronous).  Polls under a
        retry.Deadline — clamped by any ambient op budget."""
        import time as _t

        dl = retry.Deadline(retry.effective_budget(timeout))
        while True:
            if self._call("complete", path=path, client=self.name,
                             block_lengths=lengths):
                return
            if dl.expired:
                raise IOError(f"complete({path}) timed out awaiting replicas")
            _t.sleep(min(0.05, max(dl.remaining(), 0.0)))

    def _write_block(self, path: str, block: bytes, retries: int = 3) -> int:
        """Block-granular pipeline recovery with capped full-jitter backoff
        between attempts (replacing the immediate hot-loop retry — the
        DataStreamer's sleepy recovery, DataStreamer.java:655); a spent
        ambient deadline stops retrying instead of sleeping into it."""
        import time as _t

        last_err: Exception | None = None
        delays = retry.backoff_delays(max(0, retries - 1),
                                      base_s=0.05, cap_s=2.0)
        for attempt in range(retries):
            dl = retry.current()
            if dl is not None:
                dl.check("block write retry")
            alloc = self._call("add_block", path=path, client=self.name)
            bid = alloc["block_id"]
            shed_hint = None
            try:
                self._stream_block(alloc, block)
                return bid
            except qos.ShedError as e:
                # structured admission refusal: retry, but wait the DN's
                # own estimate instead of blind backoff
                last_err = e
                shed_hint = e.retry_after_s
                _M.incr("write_sheds_seen")
                self._call("abandon_block", path=path, client=self.name,
                              block_id=bid)
                # futile retry: the DN says admission needs longer than
                # the whole remaining budget — surface the shed now
                # instead of sleeping the deadline away
                if shed_hint and dl is not None \
                        and shed_hint > dl.remaining():
                    raise last_err
            except (OSError, ConnectionError, IOError) as e:
                last_err = e
                _M.incr("block_write_retries")
                self._call("abandon_block", path=path, client=self.name,
                              block_id=bid)
            if attempt < retries - 1:
                delay = next(delays)
                if shed_hint:
                    delay = max(delay, shed_hint)
                if dl is not None:
                    delay = min(delay, dl.remaining())
                if delay > 0:
                    _t.sleep(delay)
        if isinstance(last_err, qos.ShedError):
            raise last_err  # keep the structured retryable type + hint
        raise IOError(f"block write failed after {retries} attempts: {last_err}")

    def _stream_block(self, alloc: dict, block: bytes) -> None:
        targets = alloc["targets"]
        sock = socket.create_connection(tuple(targets[0]["addr"]),
                                        timeout=retry.effective_budget(120.0))
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock = dt.secure_socket(sock, alloc.get("token"),
                                    self.config.encrypt_data_transfer)
            dt.send_op(sock, dt.WRITE_BLOCK, block_id=alloc["block_id"],
                       gen_stamp=alloc["gen_stamp"], scheme=alloc["scheme"],
                       token=alloc.get("token"), targets=targets[1:],
                       storage_type=targets[0].get("storage_type"),
                       _client=self.name)
            npkts = dt.stream_bytes(sock, block, self.config.packet_size)
            # Drain per-packet acks; the final one carries pipeline status.
            # A shed ack's seqno field carries the DN's retry-after hint in
            # ms (datatransfer.py ACK_SHED — the block was refused at
            # admission, nothing was stored).
            status = dt.ACK_SUCCESS
            hint = 0
            for _ in range(npkts):
                hint, status = dt.read_ack(sock)
            if status == dt.ACK_SHED:
                raise qos.ShedError(
                    f"block {alloc['block_id']} shed at admission",
                    retry_after_s=hint / 1e3)
            if status != dt.ACK_SUCCESS:
                raise IOError(f"pipeline returned status {status}")
        finally:
            sock.close()

    # ------------------------------------------------------------------ read

    def read(self, path: str, offset: int = 0, length: int = -1) -> bytes:
        """Read [offset, offset+length) of a file (whole file by default)."""
        with self._op_deadline(), _TR.span("read") as sp:
            sp.annotate("path", path)
            loc = self._cached_meta("get_block_locations", path)
            if not loc.get("ec") and any(not b["locations"]
                                         for b in loc["blocks"]):
                # Observer block maps are eventually consistent: IBRs race
                # the journal tail, so a freshly-completed block can show
                # zero locations there even after msync (which fences the
                # NAMESPACE txid only).  Bounce the locations fetch to the
                # active (_sid in kwargs skips observer routing) and drop
                # the stale cache entry rather than failing the read.
                _M.incr("observer_empty_locations")
                with self._meta_lock:
                    self._meta_cache.pop(("get_block_locations", path),
                                         None)
                loc = self._call("get_block_locations", path=path,
                                 _sid=getattr(self._nn, "last_seen_txid",
                                              0))
            total = loc["length"]
            end = total if length < 0 else min(offset + length, total)
            if offset >= end:
                return b""
            if loc.get("ec"):
                from hdrf_tpu.client.striped import StripedReader

                data = StripedReader(self).read(loc, offset, end)
                _M.incr("files_read")
                _M.incr("bytes_read", len(data))
                return data
            out = bytearray()
            pos = 0
            for binfo in loc["blocks"]:
                blen = binfo["length"]
                bstart, bend = pos, pos + blen
                pos = bend
                if bend <= offset or bstart >= end:
                    continue
                lo = max(offset, bstart) - bstart
                hi = min(end, bend) - bstart
                out += self._read_block(binfo, lo, hi - lo)
            if loc.get("encrypted") and out:
                # CryptoInputStream role: offset-aware ChaCha20 decrypt —
                # seek the keystream to the 64-byte block containing
                # ``offset`` and discard the intra-block prefix.  The DEK
                # rides the locations response (FileEncryptionInfo).
                enc = loc.get("encryption") or self._call("decrypt_edek",
                                                          path=path)
                pre = offset % 64
                ks = native.chacha20_xor(
                    bytes(enc["dek"]), bytes(enc["iv"]),
                    b"\x00" * pre + bytes(out), counter=1 + offset // 64)
                out = ks[pre:]
                _M.incr("encrypted_reads")
            _M.incr("files_read")
            _M.incr("bytes_read", len(out))
            return bytes(out)

    def _read_block(self, binfo: dict, offset: int, length: int) -> bytes:
        locations = binfo["locations"]
        if not locations:
            raise IOError(f"block {binfo['block_id']} has no live locations")
        # Short-circuit: a co-located DN passes the replica fd over its unix
        # socket and we pread directly.  Granted fds are CACHED across
        # reads (ShortCircuitCache.java:72), each guarded by a DN-owned
        # shm slot: delete/append revokes the slot and the next read
        # re-fetches instead of serving stale bytes.
        if self.config.short_circuit:
            if self._sc_cache is None:
                from hdrf_tpu.server.shortcircuit import ShortCircuitCache

                self._sc_cache = ShortCircuitCache()
            for loc in locations:
                sc = loc.get("sc_path")
                if sc and loc["addr"][0] in ("127.0.0.1", "localhost"):
                    data = self._sc_cache.read(sc, binfo["block_id"], offset,
                                               length,
                                               token=binfo.get("token"),
                                               client_name=self.name)
                    if data is not None:
                        _M.incr("short_circuit_reads")
                        return data
        if self.config.hedged_reads and len(locations) > 1:
            return self._read_hedged(binfo, locations, offset, length)
        last_err: Exception | None = None
        for loc in locations:  # failover across replicas
            try:
                return self._read_from(tuple(loc["addr"]), binfo["block_id"],
                                       offset, length,
                                       token=binfo.get("token"))
            except (OSError, ConnectionError, IOError) as e:
                last_err = e
                _M.incr("read_failovers")
        raise IOError(f"all {len(locations)} locations failed for block "
                      f"{binfo['block_id']}: {last_err}")

    def _read_hedged(self, binfo: dict, locations: list, offset: int,
                     length: int) -> bytes:
        """Tied-request replica reads (the reference's hedged-read pool,
        DFSInputStream.java:1131 hedgedFetchBlockByteRange, rebuilt on
        utils/retry.hedged_quorum): the first location is the primary leg;
        the rest launch once the primary exceeds the rolling-p95 latency
        deadline (ClientConfig.read_hedge_p95_mult over the client's block-
        read window) — or immediately on primary failure, preserving the
        serial loop's fail-fast failover."""
        def leg(loc):
            def run():
                t0 = time.monotonic()
                data = self._read_from(tuple(loc["addr"]),
                                       binfo["block_id"], offset, length,
                                       token=binfo.get("token"))
                self._read_lat.add(time.monotonic() - t0)
                return data
            return run

        s = self._read_lat.summary()
        hedge_after = max(
            (s["p95"] if s else 0.0) * self.config.read_hedge_p95_mult,
            self.config.read_hedge_floor_s)
        try:
            wins, errors, _hedged = retry.hedged_quorum(
                [leg(locations[0])], [leg(l) for l in locations[1:]],
                k=1, hedge_after_s=hedge_after,
                on_hedge=lambda: _M.incr("read_hedges_fired"))
        except retry.QuorumFailed as e:
            _M.incr("read_failovers", len(locations))
            raise IOError(f"all {len(locations)} locations failed for block "
                          f"{binfo['block_id']}: {e}") from e
        if errors:
            _M.incr("read_failovers", len(errors))
        idx, data = wins[0]
        if idx >= 1:  # a hedge leg answered first (leg 0 is the primary)
            _M.incr("read_hedge_wins")
        return data

    # ------------------------------------------------------- file checksum

    def get_file_checksum(self, path: str) -> dict:
        """Whole-file checksum from per-block chunk CRCs
        (FileChecksumHelper.java:56; BlockChecksumHelper.java:61 computes
        the per-block half on the DN, :328 the striped block-group
        variant).  COMPOSITE-CRC32C semantics (HDFS-13056): the combinable
        CRC of the LOGICAL byte stream, so identical content yields the
        identical checksum across replicated and EC-striped layouts — and
        equals ``crc32c(file_bytes)`` outright.  No block data is read
        except partial/misaligned EC tail cells.  Encryption-zone files
        checksum their stored ciphertext (as the reference does)."""
        from hdrf_tpu.utils.checksum import compose_chunks, crc32c_combine

        loc = self._call("get_block_locations", path=path)
        crc, pos = 0, 0
        if loc.get("ec"):
            from hdrf_tpu.ops import rs

            k, _m, cell = rs.parse_policy(loc["ec"])
            for grp in loc["groups"]:
                glen = max(grp["length"], 0)
                shard_info: dict[int, tuple] = {}

                def info_of(i, _grp=grp, _cache=shard_info):
                    if i not in _cache:
                        _cache[i] = self._block_checksum(_grp["blocks"][i])
                    return _cache[i]

                gpos, c = 0, 0
                while gpos < glen:
                    take = min(cell, glen - gpos)
                    row = c // k
                    done = False
                    if take == cell:   # tail cells never need the DN CRCs
                        crcs, cchunk, _ln = info_of(c % k)
                        if cell % cchunk == 0:
                            i0 = row * cell // cchunk
                            for cc in crcs[i0:i0 + cell // cchunk]:
                                crc = cc if pos == 0 else \
                                    crc32c_combine(crc, cc, cchunk)
                                pos += cchunk
                            done = True
                    if not done:
                        # partial tail cell (or cell not a chunk multiple):
                        # the stored chunk CRC covers the zero PAD too, so
                        # read the logical bytes and hash directly
                        piece = self.read(path, offset=pos, length=take)
                        pc = native.crc32c(piece)
                        crc = pc if pos == 0 else \
                            crc32c_combine(crc, pc, len(piece))
                        pos += take
                    gpos += take
                    c += 1
        else:
            for binfo in loc["blocks"]:
                blen = max(binfo["length"], 0)
                if blen == 0:
                    continue
                crcs, cchunk, ln = self._block_checksum(binfo)
                if ln == blen:
                    bcrc, _ = compose_chunks(crcs, cchunk, blen)
                else:
                    # replica length disagrees with the located length (the
                    # block grew past an hflush, or pipeline recovery
                    # resized it): the tail chunk CRC no longer covers the
                    # right span, so hash the block's bytes directly
                    bcrc = native.crc32c(
                        self.read(path, offset=pos, length=blen))
                crc = bcrc if pos == 0 else crc32c_combine(crc, bcrc, blen)
                pos += blen
        _M.incr("file_checksums")
        return {"algorithm": "COMPOSITE-CRC32C", "length": pos,
                "crc": crc, "bytes": f"{crc:08x}"}

    def _block_checksum(self, binfo: dict) -> tuple[list[int], int, int]:
        """(chunk_crcs, chunk_size, logical_len) via the BLOCK_CHECKSUM op,
        failing over across replica locations."""
        last_err: Exception | None = None
        for loc in binfo["locations"]:
            sock = None
            try:
                sock = socket.create_connection(tuple(loc["addr"]),
                                                timeout=60)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                sock = dt.secure_socket(sock, binfo.get("token"),
                                        self.config.encrypt_data_transfer)
                dt.send_op(sock, dt.BLOCK_CHECKSUM,
                           block_id=binfo["block_id"],
                           token=binfo.get("token"))
                hdr = recv_frame(sock)
                if hdr["status"] != 0:
                    raise IOError(f"{hdr['error']}: {hdr['message']}")
                return (list(hdr["checksums"]), hdr["checksum_chunk"],
                        hdr["logical_len"])
            except (OSError, ConnectionError, IOError) as e:
                last_err = e
            finally:
                if sock is not None:
                    sock.close()
        raise IOError(f"block checksum failed for {binfo['block_id']}: "
                      f"{last_err}")

    def _read_from(self, addr: tuple[str, int], block_id: int, offset: int,
                   length: int, token: dict | None = None) -> bytes:
        sock = socket.create_connection(addr,
                                        timeout=retry.effective_budget(120.0))
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock = dt.secure_socket(sock, token,
                                    self.config.encrypt_data_transfer)
            dt.send_op(sock, dt.READ_BLOCK, block_id=block_id, offset=offset,
                       length=length, token=token, _client=self.name)
            hdr = recv_frame(sock)
            if hdr["status"] != 0:
                if hdr.get("error") == "ShedError":
                    # structured admission refusal: typed + retry-after so
                    # callers can wait exactly as long as the DN estimated
                    raise qos.ShedError(
                        f"datanode shed: {hdr.get('message', '')}",
                        retry_after_s=float(hdr.get("retry_after_s") or 0.0))
                raise IOError(f"datanode error: {hdr['error']}: {hdr['message']}")
            data = dt.collect_packets(sock)
            if len(data) != hdr["length"]:
                raise IOError(f"short read: {len(data)} != {hdr['length']}")
            # End-to-end verify when the range aligns with checksum chunks
            # (full-block reads always do).
            cchunk = hdr["checksum_chunk"]
            if hdr["checksums"] and offset % cchunk == 0:
                stored = hdr["checksums"][offset // cchunk:]
                for i in range(0, len(data) // cchunk + (1 if len(data) % cchunk else 0)):
                    piece = data[i * cchunk:(i + 1) * cchunk]
                    if (len(piece) == cchunk or offset + len(data) == hdr["logical_len"]) \
                            and i < len(stored):
                        if native.crc32c(piece) != stored[i]:
                            raise IOError(f"checksum mismatch at chunk {i}")
            return data
        finally:
            sock.close()


class HdrfOutputStream:
    """Streaming output with mid-write durability (DFSOutputStream analog).

    ``write`` buffers; a full block's bytes stream down one pipeline socket
    held open across calls (DataStreamer's block lifetime).  ``hflush``
    pushes the buffered bytes as packets whose final one carries FLAG_FLUSH
    — every pipeline DN exposes the prefix to readers before acking — then
    persists the visible length at the NameNode (ClientProtocol.fsync), so
    a NEW reader sees every hflush'd byte (DFSOutputStream.java:573).
    ``hsync`` flags FLAG_SYNC instead: DNs also fsync the partial replica,
    so the prefix survives a DataNode crash (:580).

    Pipeline failure before any flush in the current block retries
    block-granularly (abandon + re-request, as HdrfClient.write does); after
    a flush the block's bytes are already reader-visible, so the error
    propagates — the caller's recovery is recover_lease + reopen, matching
    the reference's semantics when pipeline recovery exhausts datanodes."""

    def __init__(self, client: HdrfClient, path: str, block_size: int):
        self._c = client
        self._path = path
        self._bs = block_size
        self._buf = bytearray()        # bytes not yet sent down the pipeline
        self._block = bytearray()      # ALL bytes of the current block (retry)
        self._lengths: dict[int, int] = {}
        self._sock = None
        self._alloc: dict | None = None
        self._seqno = 0
        self._flushed_in_block = False
        self._closed = False
        import time as _t
        self._last_renew = _t.monotonic()

    # ------------------------------------------------------------- pipeline

    def _open_pipeline(self) -> None:
        alloc = self._c._call("add_block", path=self._path,
                              client=self._c.name)
        targets = alloc["targets"]
        sock = socket.create_connection(tuple(targets[0]["addr"]),
                                        timeout=120)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock = dt.secure_socket(sock, alloc.get("token"),
                                self._c.config.encrypt_data_transfer)
        dt.send_op(sock, dt.WRITE_BLOCK, block_id=alloc["block_id"],
                   gen_stamp=alloc["gen_stamp"], scheme="direct",
                   token=alloc.get("token"), targets=targets[1:],
                   storage_type=targets[0].get("storage_type"),
                   _client=self._c.name)
        self._sock, self._alloc, self._seqno = sock, alloc, 0

    def _teardown(self) -> None:
        if self._sock is not None:
            self._sock.close()
        self._sock = self._alloc = None
        self._seqno = 0
        self._flushed_in_block = False

    def _send(self, flags: int = 0, last: bool = False) -> None:
        """Packetize the unsent buffer; ``flags`` ride the FINAL packet of
        the batch (the flush barrier), ``last`` ends the block.  Drains one
        ack per packet sent — the final ack carries aggregated downstream
        status."""
        if self._sock is None:
            self._open_pipeline()
        psz = self._c.config.packet_size
        pkts: list[bytes] = [bytes(self._buf[i:i + psz])
                             for i in range(0, len(self._buf), psz)]
        if last:
            pkts.append(b"")           # empty LAST trailer
        elif flags and not pkts:
            pkts.append(b"")           # pure flush marker, no new bytes
        if not pkts:
            return
        del self._buf[:]
        sent = 0
        status = dt.ACK_SUCCESS
        for i, p in enumerate(pkts):
            fin = i == len(pkts) - 1
            dt.write_packet(self._sock, self._seqno, p,
                            last=last and fin,
                            flags=flags if fin and not last else 0)
            self._seqno += 1
            sent += 1
        for _ in range(sent):
            _, st = dt.read_ack(self._sock)
            status = max(status, st)
        if status != dt.ACK_SUCCESS:
            raise IOError(f"pipeline returned status {status}")

    def _finish_block(self) -> None:
        """End the current block: empty LAST packet, final aggregated ack,
        record its length."""
        if self._sock is None and not self._block:
            return
        self._send(last=True)
        bid = self._alloc["block_id"]
        self._lengths[bid] = len(self._block)
        self._last_finished = (bid, len(self._block))
        self._sock.close()
        self._sock = self._alloc = None
        self._seqno = 0
        del self._block[:]
        self._flushed_in_block = False

    def _retryable(self, op) -> None:
        """Run a pipeline op; on connection failure with no flush exposure
        in this block, abandon and replay the whole current block on a
        fresh pipeline (block-granular recovery)."""
        try:
            op()
            return
        except (OSError, ConnectionError, IOError):
            if self._flushed_in_block:
                raise
            _M.incr("block_write_retries")
            bid = self._alloc["block_id"] if self._alloc else None
            self._teardown()
            if bid is not None:
                self._c._call("abandon_block", path=self._path,
                              client=self._c.name, block_id=bid)
        self._buf = bytearray(self._block)   # replay from block start
        op()

    # ------------------------------------------------------------------ api

    def write(self, data: bytes) -> None:
        assert not self._closed, "stream closed"
        import time as _t

        if _t.monotonic() - self._last_renew > 20.0:
            self._c._call("renew_lease", client=self._c.name)
            self._last_renew = _t.monotonic()
        off = 0
        while off < len(data):
            room = self._bs - len(self._block)
            take = data[off:off + min(room, len(data) - off)]
            self._buf += take
            self._block += take
            off += len(take)
            if len(self._block) >= self._bs:
                self._retryable(self._finish_block)
                # Persist the finished block's length while the file stays
                # open (the reference commits the previous block's length
                # in the next addBlock call) — without it a reader of the
                # open file sees length 0 for this block until complete().
                # OUTSIDE the retry wrapper: the block is already finalized
                # on every DN, and a replay here would allocate a duplicate.
                bid, ln = self._last_finished
                self._c._call("fsync", path=self._path, client=self._c.name,
                              block_id=bid, length=ln)

    def hflush(self, sync: bool = False) -> None:
        """Push buffered bytes to every pipeline DN and make them visible
        to new readers; ``sync=True`` (= hsync) also fsyncs each replica."""
        assert not self._closed, "stream closed"
        if not self._block and not self._buf:
            return  # nothing in the current block; prior blocks are final
        flag = dt.FLAG_SYNC if sync else dt.FLAG_FLUSH
        self._retryable(lambda: self._send(flags=flag))
        self._flushed_in_block = True
        self._c._call("fsync", path=self._path, client=self._c.name,
                      block_id=self._alloc["block_id"],
                      length=len(self._block))
        _M.incr("hsyncs" if sync else "hflushes")

    def hsync(self) -> None:
        self.hflush(sync=True)

    def close(self) -> None:
        if self._closed:
            return
        if self._block or self._buf or self._sock is not None:
            self._retryable(self._finish_block)
        self._c._complete(self._path, self._lengths)
        self._closed = True
        _M.incr("files_written")

    def abort(self) -> None:
        """Tear the stream down without completing the file: the pipeline
        socket closes (the DN persists the acked prefix as a partial
        replica) and the dangling lease is left for lease recovery — the
        DFSOutputStream.abort analog."""
        self._teardown()
        self._closed = True

    def __enter__(self) -> "HdrfOutputStream":
        return self

    def __exit__(self, *exc) -> None:
        if exc[0] is None:
            self.close()
        else:
            self.abort()
