"""Client-side EC striping: the DFSStripedOutputStream.java:81 /
DFSStripedInputStream + StripedBlockUtil analog.

Layout (HDFS-compatible cell striping): the file is cut into ``cell``-byte
cells laid round-robin over k data shards — cell c lives in shard ``c % k``
at row ``c // k``.  One *block group* covers ``k * block_size`` logical bytes
and produces k data + m parity internal blocks on k+m distinct DataNodes.
Parity is computed by the MXU bit-matrix RS kernel (ops/rs.py); data shards
are stored zero-padded to whole stripes (the pad never leaves the group:
reads slice to the group's logical length).

Reads fetch the k data shards; any missing/failed shard triggers a parity
fetch + RS decode on the spot (the degraded-read path,
StripedBlockUtil.decodeAndFillBuffer analog).
"""

from __future__ import annotations

import socket

import numpy as np

from hdrf_tpu.ops import rs
from hdrf_tpu.proto import datatransfer as dt
from hdrf_tpu.utils import metrics

_M = metrics.registry("client_ec")


def layout_shards(data: bytes, k: int, cell: int) -> np.ndarray:
    """Round-robin cell layout -> u8[k, L] zero-padded data shards."""
    n = len(data)
    ncells = max((n + cell - 1) // cell, 1)
    rows = (ncells + k - 1) // k
    L = rows * cell
    shards = np.zeros((k, L), dtype=np.uint8)
    a = np.frombuffer(data, dtype=np.uint8)
    for c in range(ncells):
        piece = a[c * cell:(c + 1) * cell]
        r = c // k
        shards[c % k, r * cell:r * cell + piece.size] = piece
    return shards


def assemble(shards: dict[int, np.ndarray], k: int, cell: int,
             length: int) -> bytes:
    """Inverse of layout_shards over the k data shards."""
    L = next(iter(shards.values())).size
    out = np.empty(length, dtype=np.uint8)
    pos = 0
    c = 0
    while pos < length:
        r = c // k
        piece = shards[c % k][r * cell:(r + 1) * cell]
        take = min(cell, length - pos)
        out[pos:pos + take] = piece[:take]
        pos += take
        c += 1
    return out.tobytes()


class StripedWriter:
    def __init__(self, client):
        self._c = client

    def write(self, path: str, data: bytes, policy: str) -> None:
        c = self._c
        k, m, cell = rs.parse_policy(policy)
        info = c._call("create", path=path, client=c.name, ec=policy)
        group_capacity = k * info["block_size"]
        lengths: dict[int, int] = {}
        off = 0
        while True:
            chunk = data[off:off + group_capacity]
            gid = self._write_group(path, chunk, k, m, cell)
            lengths[gid] = len(chunk)
            off += group_capacity
            if off >= len(data):
                break
        c._complete(path, lengths)
        _M.incr("ec_files_written")
        _M.incr("ec_bytes_written", len(data))

    def _write_group(self, path: str, chunk: bytes, k: int, m: int,
                     cell: int) -> int:
        c = self._c
        alloc = c._call("add_block_group", path=path, client=c.name)
        assert alloc["k"] == k and alloc["m"] == m
        shards = layout_shards(chunk, k, cell)
        parity = rs.rs_encode(shards, k, m)
        allsh = np.concatenate([shards, parity])
        for blk, shard in zip(alloc["blocks"], allsh):
            self._send_shard(blk, alloc["gen_stamp"], shard.tobytes())
        return alloc["group_id"]

    def _send_shard(self, blk: dict, gen_stamp: int, shard: bytes) -> None:
        c = self._c
        sock = socket.create_connection(tuple(blk["target"]["addr"]),
                                        timeout=120)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock = dt.secure_socket(sock, blk.get("token"),
                                    c.config.encrypt_data_transfer)
            dt.send_op(sock, dt.WRITE_BLOCK, block_id=blk["block_id"],
                       gen_stamp=gen_stamp, scheme="direct",
                       token=blk.get("token"), targets=[])
            n = dt.stream_bytes(sock, shard, c.config.packet_size)
            status = dt.ACK_SUCCESS
            for _ in range(n):
                _, status = dt.read_ack(sock)
            if status != dt.ACK_SUCCESS:
                raise IOError(f"shard write returned {status}")
        finally:
            sock.close()


class StripedReader:
    def __init__(self, client):
        self._c = client

    def read(self, loc: dict, offset: int, end: int) -> bytes:
        """Read [offset, end) of an EC file given its location response."""
        k, m, cell = rs.parse_policy(loc["ec"])
        out = bytearray()
        pos = 0
        for grp in loc["groups"]:
            glen = grp["length"]
            gstart, gend = pos, pos + glen
            pos = gend
            if gend <= offset or gstart >= end:
                continue
            lo = max(offset, gstart) - gstart
            hi = min(end, gend) - gstart
            out += self._read_group(grp, k, m, cell, glen, lo, hi)
        return bytes(out)

    def _read_group(self, grp: dict, k: int, m: int, cell: int, glen: int,
                    lo: int, hi: int) -> bytes:
        """Bytes [lo, hi) of one group, reading only the stripe rows that
        overlap the range (O(length) network cost, not O(group)); the
        degraded path fetches the SAME row range from parity shards — RS is
        per-byte-position, so decode works row-wise."""
        stripe = k * cell
        row_lo, row_hi = lo // stripe, (hi + stripe - 1) // stripe
        soff, slen = row_lo * cell, (row_hi - row_lo) * cell
        shards: dict[int, np.ndarray] = {}
        failed: list[int] = []
        for i in range(k):
            data = self._try_read_shard(grp["blocks"][i], soff, slen)
            if data is None:
                failed.append(i)
            else:
                shards[i] = np.frombuffer(data, dtype=np.uint8)
        if failed:
            _M.incr("ec_degraded_reads")
            for i in range(k, k + m):
                if len(shards) >= k:
                    break
                data = self._try_read_shard(grp["blocks"][i], soff, slen)
                if data is not None:
                    shards[i] = np.frombuffer(data, dtype=np.uint8)
            if len(shards) < k:
                raise IOError(
                    f"EC group {grp['group_id']}: only {len(shards)} of "
                    f"{k}+{m} shards readable")
            shards.update(rs.rs_decode(shards, k, m, want=failed))
        # assemble the row window, then slice the requested bytes
        out = np.empty((row_hi - row_lo) * stripe, dtype=np.uint8)
        for c in range(row_lo * k, row_hi * k):
            r = c // k - row_lo
            out[(c - row_lo * k) * cell:(c - row_lo * k + 1) * cell] = \
                shards[c % k][r * cell:(r + 1) * cell]
        base = row_lo * stripe
        return out[lo - base:hi - base].tobytes()

    def _try_read_shard(self, blk: dict, offset: int,
                        length: int) -> bytes | None:
        for locd in blk["locations"]:
            try:
                return dt.fetch_block(tuple(locd["addr"]), blk["block_id"],
                                      offset, length,
                                      token=blk.get("token"),
                                      encrypt=self._c.config
                                      .encrypt_data_transfer)
            except (OSError, ConnectionError, IOError):
                _M.incr("ec_shard_read_failures")
        return None
